"""EPLB — expert-parallelism load balancing with redundant experts.

Reference: SGLang EPLB (redundant experts rebalanced from observed token
counts, docs/backends/sglang/expert-distribution-eplb.md). Here the engine
owns it (models/eplb.py + the remap tables in models/moe.py): R extra
physical expert slots, per-layer routing tables in the params pytree,
runtime rebalance with zero recompiles.

The load-bearing invariant mirrors speculative decoding's: a rebalance
moves WHERE expert compute runs, never WHAT it computes — outputs are
token-identical before and after.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import eplb, moe, registry
from dynamo_tpu.models.moe import MoeConfig
from dynamo_tpu.parallel.mesh import AXIS_TP, make_mesh
from dynamo_tpu.runtime import Context

# ----------------------------------------------------------------- planner


def test_plan_waterfills_and_spreads_shards():
    E, R, ep = 8, 4, 4
    counts = np.array([100, 80, 60, 40, 5, 5, 5, 5], float)
    p = eplb.plan(counts, E, R, ep=ep)
    # replicas go to the hottest experts
    assert p.nrep[0] >= 2 and p.nrep[1] >= 2
    assert p.nrep[4:].max() == 1
    # every replica slot serves the expert its table claims
    for e in range(E):
        for j in range(p.nrep[e]):
            assert p.phys_src[p.slots[e, j]] == e
    # padded columns stay valid
    assert (p.slots >= 0).all() and (p.slots < E + R).all()
    # the plan must beat the no-replica layout on the EPLB objective
    base = eplb.plan(counts, E, 0, ep=ep)
    assert p.max_shard_load(counts, ep) < base.max_shard_load(counts, ep)


def test_plan_replicates_one_ultra_hot_expert_many_times():
    E, R = 4, 4
    counts = np.array([1000, 1, 1, 1], float)
    p = eplb.plan(counts, E, R, ep=4)
    assert p.nrep[0] == R + 1  # water-filling pours every replica on it


def test_plan_rejects_unshardable_layout():
    with pytest.raises(ValueError, match="divide"):
        eplb.plan(np.ones(8), 8, 3, ep=4)  # 11 slots over 4 shards


def test_more_replicas_than_experts():
    """R > E: default seeding round-robins replicas over all experts, and
    the expanded stacks/tables stay consistent."""
    cfg = MoeConfig.tiny_moe(redundant_experts=8)  # E=4, R=8 -> 12 slots
    slots, nrep, src = moe.default_eplb_tables(cfg)
    assert (nrep == 3).all()               # every expert gets 2 replicas
    assert list(src) == [0, 1, 2, 3, 0, 1, 2, 3]
    params = registry.init_params(jax.random.PRNGKey(3), cfg)
    lp = params["layers"][0]
    assert lp["w_gate"].shape[0] == 12
    # replica slot E+i carries expert (i % E)'s weights
    np.testing.assert_array_equal(
        np.asarray(lp["w_gate"][4]), np.asarray(lp["w_gate"][0])
    )
    np.testing.assert_array_equal(
        np.asarray(lp["w_gate"][11]), np.asarray(lp["w_gate"][3])
    )


# ------------------------------------------------- remap + forward equality

CFG0 = MoeConfig.tiny_moe()
CFG2 = MoeConfig.tiny_moe(redundant_experts=4)


def _tokens(n=24):
    return jnp.asarray([(i * 37 + 11) % 500 for i in range(n)], jnp.int32)


def _dense_logits(cfg, params, toks):
    from dynamo_tpu.ops import attention as att

    def attend(q, k_new, v_new, layer_idx, **extra):
        return att.causal_attention(q, k_new, v_new, **extra)

    h = moe.forward(params, cfg, toks, jnp.arange(len(toks)), attend)
    return moe.lm_logits(params, cfg, h)


@pytest.mark.slow
def test_expanded_params_match_logical_model():
    """Same logical weights, R=4 physical slots, EP over 4 shards: the
    remapped shard_map forward equals the replicated-logical forward."""
    params0 = registry.init_params(jax.random.PRNGKey(0), CFG0)
    params2 = registry.init_params(jax.random.PRNGKey(0), CFG2)
    toks = _tokens()

    mesh = make_mesh(tp=4, devices=jax.devices()[:4])
    fwd0 = registry.forward_fn(CFG0, mesh)
    fwd2 = registry.forward_fn(CFG2, mesh)

    from dynamo_tpu.ops import attention as att

    def attend(q, k_new, v_new, layer_idx, **extra):
        return att.causal_attention(q, k_new, v_new, **extra)

    with mesh:
        h0 = fwd0(params0, CFG0, toks, jnp.arange(len(toks)), attend)
        h2 = fwd2(params2, CFG2, toks, jnp.arange(len(toks)), attend)
    np.testing.assert_allclose(
        np.asarray(h0), np.asarray(h2), rtol=2e-4, atol=2e-4
    )


def test_rebalance_is_output_invariant():
    """apply_plan moves replicas around; the forward is unchanged."""
    params = registry.init_params(jax.random.PRNGKey(1), CFG2)
    toks = _tokens()
    before = _dense_logits(CFG2, params, toks)

    counts = np.array([50, 1, 40, 1], float)
    p = eplb.plan(counts, CFG2.num_experts, CFG2.redundant_experts, ep=4)
    params["layers"] = [
        eplb.apply_plan(lp, p) if "eplb_slots" in lp else lp
        for lp in params["layers"]
    ]
    after = _dense_logits(CFG2, params, toks)
    np.testing.assert_allclose(
        np.asarray(before), np.asarray(after), rtol=1e-5, atol=1e-5
    )

    # and through the EP shard_map path too
    mesh = make_mesh(tp=4, devices=jax.devices()[:4])
    fwd = registry.forward_fn(CFG2, mesh)
    from dynamo_tpu.ops import attention as att

    def attend(q, k_new, v_new, layer_idx, **extra):
        return att.causal_attention(q, k_new, v_new, **extra)

    with mesh:
        h = fwd(params, CFG2, toks, jnp.arange(len(toks)), attend)
    assert bool(jnp.isfinite(h).all())


def test_probe_counts_sum_to_tokens_times_k():
    params = registry.init_params(jax.random.PRNGKey(2), CFG2)
    toks = _tokens(16)
    counts = np.asarray(
        eplb.probe_expert_load(params, CFG2, toks, jnp.arange(16))
    )
    assert counts.shape == (CFG2.num_layers, CFG2.num_experts)
    expect = 16 * CFG2.num_experts_per_tok
    assert (counts.sum(axis=1) == expect).all()


# ------------------------------------------------------------- engine e2e


def preq(rid, n=16):
    return PreprocessedRequest(
        request_id=rid, model="m",
        token_ids=[(i * 13 + 5) % 500 for i in range(12)],
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def collect(eng, req):
    toks = []
    async for out in eng.generate(req, Context()):
        toks.extend(out.token_ids)
    return toks


@pytest.mark.slow
async def test_engine_serves_and_rebalances_identically():
    """tiny-moe with EPLB over tp=4: serve greedily, measure the load,
    rebalance mid-serving, serve the same prompt again — token-identical
    (the rebalance is invisible to outputs by construction)."""
    cfg = TpuEngineConfig(
        model=CFG2, num_blocks=64, block_size=4, max_batch_size=2,
        max_context=256, prefill_buckets=(16, 32), decode_steps=4,
        decode_pipeline=1, tp=4,
    )
    e = TpuEngine(cfg, mesh=make_mesh(tp=4, devices=jax.devices()[:4]))
    try:
        first = await collect(e, preq("a"))
        counts = e.measure_expert_load([(i * 7) % 500 for i in range(32)])
        assert counts.shape == (CFG2.num_layers, CFG2.num_experts)
        summary = e.eplb_rebalance(counts.sum(axis=0))
        assert summary["layers"] == CFG2.num_layers
        assert summary["redundant_experts"] == CFG2.redundant_experts
        again = await collect(e, preq("b"))
        assert again == first
        # rebalance must preserve the expert-dim sharding (an indexed
        # gather alone would come back replicated)
        lp = e.params["layers"][0]
        spec = lp["w_gate"].sharding.spec
        assert spec and spec[0] == AXIS_TP, spec
        # wrong-length counts fail loudly BEFORE any mutation
        with pytest.raises(ValueError, match="counts shape"):
            e.eplb_rebalance(np.ones(3))
        with pytest.raises(ValueError, match="counts shape"):
            e.eplb_rebalance(np.ones((1, CFG2.num_experts)))
    finally:
        e.stop()


def test_engine_rejects_unshardable_eplb():
    bad = MoeConfig.tiny_moe(redundant_experts=3)  # 7 slots over tp=4
    cfg = TpuEngineConfig(
        model=bad, num_blocks=64, block_size=4, max_batch_size=2,
        max_context=256, prefill_buckets=(16, 32), decode_steps=4,
        decode_pipeline=1, tp=4,
    )
    with pytest.raises(ValueError, match="divide"):
        TpuEngine(cfg, mesh=make_mesh(tp=4, devices=jax.devices()[:4]))
