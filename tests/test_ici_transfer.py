"""ICI device-to-device KV transfer (engine/transfer.py IciKvMover).

The round-3 verdict's item #3: the same-slice fast path must move pages
HBM->HBM (gather on the source mesh -> device_put reshard -> scatter on the
destination mesh) and be BIT-IDENTICAL to the DCN host-staging protocol.
Reference analog: NIXL GPU<->GPU RDMA (lib/memory/src/nixl.rs:13,
docs/design_docs/disagg_serving.md:20,54).
"""


import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.engine.transfer import LOCAL_SERVERS
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.runtime import Context

BS = 4


def _cfg(tp=1, devices=None):
    mcfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
    )
    return TpuEngineConfig(
        model=mcfg, num_blocks=32, block_size=BS, max_batch_size=2,
        max_context=128, prefill_buckets=(16, 32, 64, 128), tp=tp,
    )


async def _prefill_src(src, prompt):
    """Run one greedy request through src so it holds committed pages."""
    req = PreprocessedRequest(
        request_id="src", model="m", token_ids=prompt,
        stop=StopConditions(max_tokens=2, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )
    async for _ in src.generate(req, Context()):
        pass


def _block_bytes(engine, hashes):
    """Concatenated bytes of every layer's K and V pages for ``hashes``."""
    ids = engine.allocator.acquire_prefix(hashes)
    assert len(ids) == len(hashes), (ids, hashes)
    try:
        out = b""
        for kc, vc in zip(engine.k_caches, engine.v_caches):
            out += np.asarray(kc[np.asarray(ids)]).tobytes()
            out += np.asarray(vc[np.asarray(ids)]).tobytes()
        return out
    finally:
        engine.allocator.release(ids)


async def _run_bit_equality(monkeypatch):
    prompt = list(range(50, 50 + 5 * BS))  # 5 full blocks, 4 committed
    devs = jax.devices()
    src = TpuEngine(_cfg(tp=2), mesh=make_mesh(tp=2, devices=devs[0:2]))
    # dst engines live on a DIFFERENT device group: the device_put hop is a
    # real cross-group copy (ICI on TPU hardware)
    dst_ici = TpuEngine(_cfg(tp=2), mesh=make_mesh(tp=2, devices=devs[2:4]))
    dst_dcn = TpuEngine(_cfg(tp=2), mesh=make_mesh(tp=2, devices=devs[4:6]))
    addr = None
    try:
        await _prefill_src(src, prompt)
        addr = await src.serve_transfer()
        from dynamo_tpu.tokens import compute_sequence_hashes

        hashes = compute_sequence_hashes(prompt, BS)[: (len(prompt) - 1) // BS]
        assert hashes

        # --- ICI path (default for a co-resident server) ---
        assert addr in LOCAL_SERVERS
        got = await dst_ici._get_transfer_client().fetch_and_import(addr, hashes)
        assert got == len(hashes) * BS

        # --- DCN path (forced over the wire) ---
        monkeypatch.setenv("DTPU_ICI_TRANSFER", "0")
        got = await dst_dcn._get_transfer_client().fetch_and_import(addr, hashes)
        assert got == len(hashes) * BS

        src_bytes = _block_bytes(src, hashes)
        ici_bytes = _block_bytes(dst_ici, hashes)
        dcn_bytes = _block_bytes(dst_dcn, hashes)
        assert ici_bytes == src_bytes, "ICI-moved pages differ from source"
        assert ici_bytes == dcn_bytes, "ICI and DCN paths disagree"
    finally:
        src.stop()
        dst_ici.stop()
        dst_dcn.stop()
        if addr is not None:
            assert addr not in LOCAL_SERVERS  # stop() deregisters


async def test_ici_bit_equality_with_dcn(monkeypatch):
    await _run_bit_equality(monkeypatch)


async def test_ici_falls_back_when_dest_full(monkeypatch):
    """Destination out of blocks: the mover returns 0/None gracefully and
    the client reports only what was imported."""
    prompt = list(range(10, 10 + 3 * BS))
    devs = jax.devices()
    src = TpuEngine(_cfg(), mesh=make_mesh(tp=1, devices=devs[0:1]))
    dst = TpuEngine(_cfg(), mesh=make_mesh(tp=1, devices=devs[1:2]))
    addr = None
    try:
        await _prefill_src(src, prompt)
        addr = await src.serve_transfer()
        from dynamo_tpu.tokens import compute_sequence_hashes

        hashes = compute_sequence_hashes(prompt, BS)[: (len(prompt) - 1) // BS]
        # exhaust the destination allocator
        hog = dst.allocator.allocate(dst.allocator.free_blocks)
        got = await dst._get_transfer_client().fetch_and_import(addr, hashes)
        assert got == 0
        dst.allocator.release(hog)
        got = await dst._get_transfer_client().fetch_and_import(addr, hashes)
        assert got == len(hashes) * BS
    finally:
        src.stop()
        dst.stop()
