"""Chunked prefill + long context in the real engine (VERDICT r2 item 2).

The reference treats chunked prefill as table stakes (lib/mocker/src/
protocols.rs:112, components/src/dynamo/trtllm/engine.py:119); here the
engine owns it: prompts longer than the largest prefill bucket run in
bounded chunks against cached prefix pages, one chunk per engine-loop tick,
with ring_extend_attention as the context-parallel chunk path (sp > 1).
"""

import asyncio

import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.runtime import Context

MODEL = LlamaConfig(
    vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
    num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
)


def engine(buckets, max_context=512, sp=1, tp=1, **kw):
    defaults = dict(
        num_blocks=256, block_size=4, max_batch_size=4,
        max_context=max_context, prefill_buckets=buckets, sp=sp, tp=tp,
    )
    defaults.update(kw)
    cfg = TpuEngineConfig(model=MODEL, **defaults)
    n = sp * tp
    return TpuEngine(cfg, mesh=make_mesh(tp=tp, sp=sp, devices=jax.devices()[:n]))


def preq(rid, tokens, n=8):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=tokens,
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def run(eng, rid, tokens, n=8):
    toks, cached = [], None
    async for out in eng.generate(preq(rid, tokens, n), Context()):
        toks.extend(out.token_ids)
        if out.annotations and "cached_tokens" in out.annotations:
            cached = out.annotations["cached_tokens"]
    return toks, cached


PROMPT = [(i * 37 + 11) % 500 for i in range(200)]


@pytest.mark.slow
async def test_chunked_equals_single_shot():
    """A prompt longer than every bucket (forcing 7 chunks of <=32) produces
    token-identical greedy output to a single-shot prefill."""
    e_big = engine(buckets=(256,))
    try:
        ref, _ = await run(e_big, "ref", PROMPT)
    finally:
        e_big.stop()
    e_chunked = engine(buckets=(16, 32))  # chunk cap 32 << 200-token prompt
    try:
        got, cached = await run(e_chunked, "chk", PROMPT)
        assert got == ref
        # prefix cache still content-addresses the chunked pages: a repeat
        # reuses all complete prompt blocks
        got2, cached2 = await run(e_chunked, "chk2", PROMPT)
        assert got2 == ref
        assert cached2 >= (len(PROMPT) - 1) // 4 * 4 - 4
    finally:
        e_chunked.stop()


@pytest.mark.slow
async def test_long_context_beyond_largest_bucket():
    """max_context 2048 with a 128-token chunk cap: a 1500-token prompt
    (12 chunks) serves end-to-end."""
    e = engine(buckets=(64, 128), max_context=2048, num_blocks=1024)
    prompt = [(i * 13 + 5) % 500 for i in range(1500)]
    try:
        toks, _ = await run(e, "long", prompt, n=4)
        assert len(toks) == 4
        # deterministic across runs
        toks2, cached = await run(e, "long2", prompt, n=4)
        assert toks2 == toks
        assert cached and cached > 1400
    finally:
        e.stop()


async def test_short_request_not_starved_by_long_prefill():
    """Chunk-per-tick + round-robin: a short prompt submitted during a long
    prefill gets its first token before the long prefill finishes."""
    e = engine(buckets=(16, 32), max_context=1024)
    long_prompt = [(i * 7 + 3) % 500 for i in range(800)]  # 25 chunks
    order = []

    async def drive(rid, tokens, n):
        async for out in e.generate(preq(rid, tokens, n), Context()):
            if out.token_ids:
                order.append(rid)
                return

    try:
        t_long = asyncio.create_task(drive("long", long_prompt, 1))
        await asyncio.sleep(0.05)  # long prefill underway
        t_short = asyncio.create_task(drive("short", list(range(20)), 1))
        await asyncio.gather(t_long, t_short)
        assert order[0] == "short", order
    finally:
        e.stop()


@pytest.mark.slow
async def test_sp_ring_prefill_matches_sp1():
    """Engine-integrated CP: chunk prefill through ring_extend_attention on
    an sp=2 mesh produces the same greedy output as sp=1."""
    e1 = engine(buckets=(16, 32))
    try:
        ref, _ = await run(e1, "a", PROMPT)
    finally:
        e1.stop()
    e2 = engine(buckets=(16, 32), sp=2)
    try:
        got, _ = await run(e2, "b", PROMPT)
        assert got == ref
    finally:
        e2.stop()


async def test_sp_with_tp_combined():
    """sp=2 x tp=2 mesh: ring chunk attention + TP-sharded projections."""
    e1 = engine(buckets=(16, 32))
    try:
        ref, _ = await run(e1, "a", PROMPT, n=4)
    finally:
        e1.stop()
    e = engine(buckets=(16, 32), sp=2, tp=2)
    try:
        got, _ = await run(e, "c", PROMPT, n=4)
        assert got == ref
    finally:
        e.stop()


@pytest.mark.slow
async def test_concurrent_identical_prompt_never_matches_unwritten_pages():
    """Regression (code-review r3): block hashes are committed only after
    their chunk's KV lands. A same-prompt request racing a chunked prefill
    must produce correct output — never sample from garbage pages."""
    e_ref = engine(buckets=(256,))
    prompt = [(i * 37 + 11) % 500 for i in range(200)]
    try:
        async def collect(eng, rid):
            toks = []
            async for out in eng.generate(preq(rid, prompt, 6), Context()):
                toks.extend(out.token_ids)
            return toks

        ref = await collect(e_ref, "ref")
    finally:
        e_ref.stop()

    e = engine(buckets=(16, 32))  # 7 chunks
    try:
        async def collect2(rid, delay):
            await asyncio.sleep(delay)
            toks = []
            async for out in e.generate(preq(rid, prompt, 6), Context()):
                toks.extend(out.token_ids)
            return toks

        a, b = await asyncio.gather(collect2("a", 0), collect2("b", 0.02))
        assert a == ref
        assert b == ref  # not poisoned by matching unwritten pages
    finally:
        e.stop()


@pytest.mark.slow
async def test_cancel_mid_prefill_frees_slot_and_poisons_nothing():
    """Killing a request mid-chunked-prefill stops chunk dispatch, frees the
    slot, and leaves no unwritten block matchable."""
    e = engine(buckets=(16, 32), max_context=1024, num_blocks=512)
    long_prompt = [(i * 7 + 3) % 500 for i in range(800)]
    ctx = Context("victim")

    async def drive():
        async for out in e.generate(preq("victim", long_prompt, 4), ctx):
            pass

    t = asyncio.create_task(drive())
    await asyncio.sleep(0.1)  # a few chunks in
    ctx.stop_generating()
    await asyncio.wait_for(t, timeout=10)
    # slot freed
    for _ in range(100):
        if all(s is None for s in e._slots):
            break
        await asyncio.sleep(0.02)
    assert all(s is None for s in e._slots)
    try:
        # a later identical request must produce the same output as a fresh
        # engine (whatever prefix it reuses was genuinely written)
        toks = []
        async for out in e.generate(preq("later", long_prompt, 4), Context()):
            toks.extend(out.token_ids)
        e2 = engine(buckets=(16, 32), max_context=1024, num_blocks=512)
        try:
            ref = []
            async for out in e2.generate(preq("r", long_prompt, 4), Context()):
                ref.extend(out.token_ids)
        finally:
            e2.stop()
        assert toks == ref
    finally:
        e.stop()
