"""KVBM runtime controller: clear_kv_blocks across tiers + HTTP fan-out.

Reference parity: lib/llm/src/block_manager/controller.rs (runtime reset /
cache-level commands) and lib/llm/src/http/clear_kv_blocks.rs (frontend op
fanning to every worker).
"""

import asyncio
import sys

import aiohttp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from test_engine import greedy_req, run_req, tiny_engine

from dynamo_tpu.kvbm.pool import KvbmTiers


def _block(i):
    return np.full((4, 2, 8), i, np.float32)


def test_tiers_clear_drops_host_and_disk(tmp_path):
    tiers = KvbmTiers(
        block_nbytes=_block(0).nbytes,
        host_capacity_bytes=_block(0).nbytes * 4,
        disk_capacity_bytes=_block(0).nbytes * 8,
        disk_path=str(tmp_path / "kv"),
    )
    for i in range(10):
        tiers.store(i + 1, _block(i))  # host spills oldest to disk
    assert len(tiers.host) > 0 and len(tiers.disk) > 0
    counts = tiers.clear()
    assert counts["g2"] > 0 and counts["g3"] > 0
    assert len(tiers.host) == 0 and len(tiers.disk) == 0
    # dropped hashes flow to the consolidated removed-event path
    evicted = set(tiers.drain_evicted())
    assert evicted.issuperset(set(range(1, counts["g2"] + 1)) - evicted or set())
    assert len(evicted) > 0
    # spill files are gone from disk
    assert not any(f.suffix == ".kv" for f in (tmp_path / "kv").iterdir())
    tiers.close()


async def test_engine_clear_kv_blocks_drops_prefix_cache():
    engine = tiny_engine()
    try:
        prompt = list(range(40, 60))
        await run_req(engine, greedy_req("a", prompt))
        assert engine.allocator.cached_blocks > 0
        res = await engine.clear_kv_blocks()
        assert res["g1"] > 0
        assert res["snapshot"]["cached_blocks"] == 0
        # second identical request: no cached prefix, but still serves
        t2, cached = await run_req(engine, greedy_req("b", prompt))
        assert len(t2) == 8
        assert not cached
        # cache rebuilds after the clear
        assert engine.allocator.cached_blocks > 0
    finally:
        engine.stop()


async def test_frontend_clear_fans_to_workers():
    """Full path: frontend POST /clear_kv_blocks -> every worker's clear
    endpoint (mocker fleet) -> per-worker results; caches actually empty."""
    from dynamo_tpu.llm import (
        ModelDeploymentCard,
        ModelManager,
        ModelWatcher,
        register_llm,
    )
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
    from dynamo_tpu.runtime import (
        DistributedRuntime,
        InProcEventPlane,
        MemKVStore,
        RouterMode,
        RuntimeConfig,
    )
    from dynamo_tpu.runtime.component import new_instance_id

    store = MemKVStore()
    plane = InProcEventPlane()

    def make_rt():
        cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
        return DistributedRuntime(cfg, store=store, event_plane=plane)

    worker_rt = await make_rt().start()
    frontend_rt = await make_rt().start()
    engines = []
    served = []
    for _ in range(2):
        iid = new_instance_id()
        eng = MockerEngine(MockEngineArgs(speedup_ratio=50.0))
        engines.append(eng)
        card = ModelDeploymentCard(
            name="clear-model", tokenizer="byte", context_length=4096,
        )
        s = await register_llm(worker_rt, eng, card, instance_id=iid)
        served.append(s)
        from dynamo_tpu.llm.serve import serve_clear_endpoint

        served.append(await serve_clear_endpoint(
            worker_rt, card.namespace, card.component, [eng], iid
        ))
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        for _ in range(100):
            p = manager.get("clear-model")
            if p and len(p.client.instances) == 2:
                break
            await asyncio.sleep(0.05)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as s:
            # populate both workers' caches (round robin)
            for i in range(4):
                r = await s.post(
                    f"{base}/v1/chat/completions",
                    json={"model": "clear-model", "max_tokens": 8,
                          "messages": [{"role": "user", "content": f"warm {i % 2}"}]},
                )
                assert r.status == 200
            assert any(len(e.kv.cached) > 0 for e in engines)
            # a bare-string levels is a 400, not a silent no-op
            r = await s.post(f"{base}/clear_kv_blocks", json={"levels": "g1"})
            assert r.status == 400
            r = await s.post(f"{base}/clear_kv_blocks", json={})
            assert r.status == 200, await r.text()
            body = await r.json()
        workers = body["cleared"]["clear-model"]
        assert len(workers) == 2
        for res in workers.values():
            assert "error" not in res, workers
            assert res["snapshot"]["cached_blocks"] == 0
        assert all(len(e.kv.cached) == 0 for e in engines)
    finally:
        await service.stop()
        await watcher.stop()
        for s in served:
            await s.stop()
        await worker_rt.shutdown()
        await frontend_rt.shutdown()
