"""SLO accounting plane (runtime/slo.py).

Class resolution + annotation propagation frontend -> engine, multi-window
attainment/burn-rate on a controlled clock, goodput counters, /debug/slo
payloads (frontend + StatusServer), class-labeled metrics hierarchy, the
planner's class_attainment feed, the flight-recorder budget breakdown, the
loadgen/profiler attainment dedupe (byte-pinned JSON), the bench detail.slo
schema, and the sim mixed-SLA accountant-vs-trace agreement.
"""

import asyncio
import json

import aiohttp

from dynamo_tpu.llm import (
    EchoEngine,
    ModelDeploymentCard,
    ModelManager,
    ModelWatcher,
    register_llm,
)
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.model_card import ModelRuntimeConfig
from dynamo_tpu.planner.metrics_source import (
    EventPlaneMetricsSource,
    FrontendStatsPublisher,
)
from dynamo_tpu.runtime import (
    DistributedRuntime,
    InProcEventPlane,
    MemKVStore,
    RouterMode,
    RuntimeConfig,
)
from dynamo_tpu.runtime import metrics as M
from dynamo_tpu.runtime import slo
from dynamo_tpu.runtime.flight_recorder import (
    FlightRecorder,
    debug_requests_payload,
    set_flight_recorder,
)
from dynamo_tpu.runtime.health import HealthState, StatusServer
from dynamo_tpu.runtime.slo import (
    SlaSpec,
    SloAccountant,
    attainment,
    bench_slo_detail,
    budget_breakdown,
    resolve_sla,
    set_slo_accountant,
    sla_classes,
    spec_from_annotations,
)


# ------------------------------------------------------ class resolution
def test_builtin_classes_and_env_overlay(monkeypatch):
    classes = sla_classes()
    assert {"interactive", "standard", "batch"} <= set(classes)
    assert classes["interactive"].ttft_target_s < classes["batch"].ttft_target_s
    monkeypatch.setenv(
        "DTPU_SLA_CLASSES", "rt:ttft=0.2,itl=0.02,deadline=5;batch:ttft=60"
    )
    classes = sla_classes()
    assert classes["rt"] == SlaSpec("rt", 0.2, 0.02, 5.0)
    # partial override inherits the built-in's unset targets
    assert classes["batch"].ttft_target_s == 60.0
    assert classes["batch"].itl_target_s == 1.0


def test_bad_env_spec_falls_back_to_builtins(monkeypatch):
    monkeypatch.setenv("DTPU_SLA_CLASSES", "oops:ttft=fast")
    classes = sla_classes()
    assert set(classes) == {"interactive", "standard", "batch"}


def test_resolve_sla_model_overrides_and_unknown():
    spec = resolve_sla("interactive", {"interactive": {"ttft_target_s": 0.3}})
    assert spec is not None and spec.ttft_target_s == 0.3
    assert spec.itl_target_s == sla_classes()["interactive"].itl_target_s
    assert resolve_sla(None).sla_class == "standard"  # default class
    assert resolve_sla("no-such-class") is None


def test_annotation_round_trip_and_malformed():
    spec = SlaSpec("interactive", 0.5, 0.05, 30.0)
    ann = {slo.ANNOTATION_SLA: spec.to_annotation(t0_ns=123)}
    back = spec_from_annotations(ann)
    assert back == spec
    assert slo.sla_t0_ns(ann) == 123
    assert spec_from_annotations({}) is None
    assert spec_from_annotations({slo.ANNOTATION_SLA: "interactive"}) is None
    assert spec_from_annotations(
        {slo.ANNOTATION_SLA: {"class": "x", "ttft_target_s": "bogus"}}
    ) is None


# ------------------------------------------------------ accountant windows
def _clocked(objective=0.9):
    t = [0.0]
    acct = SloAccountant(clock=lambda: t[0], objective=objective)
    return t, acct


SPEC = SlaSpec("interactive", ttft_target_s=0.5, itl_target_s=0.05)


def test_multi_window_rolling_attainment():
    t, acct = _clocked()
    for _ in range(10):  # meets at t=5
        t[0] = 5.0
        acct.record("m", SPEC, ttft_s=0.1, itl_s=0.01, output_tokens=4)
    t[0] = 100.0
    for _ in range(5):  # misses at t=100
        acct.record("m", SPEC, ttft_s=2.0, itl_s=0.01, output_tokens=4)
    # 1m window at t=100 only sees the misses; 5m/1h/total see everything
    assert acct.attainment("m", "interactive", "1m", "ttft") == 0.0
    assert acct.attainment("m", "interactive", "5m", "ttft") == 10 / 15
    assert acct.attainment("m", "interactive", "1h", "ttft") == 10 / 15
    assert acct.attainment("m", "interactive", "total", "ttft") == 10 / 15
    # an hour later the rolling windows are empty but the total persists
    t[0] = 3700.0 + 100.0
    assert acct.attainment("m", "interactive", "1h", "ttft") is None
    assert acct.attainment("m", "interactive", "total", "ttft") == 10 / 15


def test_burn_rate_semantics():
    t, acct = _clocked(objective=0.9)
    for ok in (True,) * 8 + (False,) * 2:  # attainment 0.8, budget 0.1
        acct.record("m", SPEC, ttft_s=0.1 if ok else 2.0, output_tokens=1)
    br = acct.burn_rate("m", "interactive", "total")
    assert abs(br - 2.0) < 1e-9  # burning 2x the allowed error rate
    assert acct.burn_rate("m", "nope", "total") is None
    # exactly on objective -> burn rate 1.0
    assert abs(slo.burn_rate(0.9, 0.9) - 1.0) < 1e-9


def test_itl_and_deadline_fold_into_combined():
    t, acct = _clocked()
    spec = SlaSpec("x", ttft_target_s=1.0, itl_target_s=0.05, deadline_s=10.0)
    assert acct.record("m", spec, ttft_s=0.1, itl_s=0.01, e2e_s=5.0)
    assert not acct.record("m", spec, ttft_s=0.1, itl_s=0.5, e2e_s=5.0)
    assert not acct.record("m", spec, ttft_s=0.1, itl_s=0.01, e2e_s=50.0)
    # unobserved ITL cannot violate
    assert acct.record("m", spec, ttft_s=0.1, itl_s=None, e2e_s=5.0)
    assert acct.attainment("m", "x", "total", "combined") == 2 / 4
    assert acct.attainment("m", "x", "total", "itl") == 2 / 3


def test_goodput_counts_only_met_requests():
    t, acct = _clocked()
    acct.record("m", SPEC, ttft_s=0.1, output_tokens=100)   # met
    acct.record("m", SPEC, ttft_s=5.0, output_tokens=40)    # violated
    snap = acct.snapshot()
    tw = snap["models"]["m"]["interactive"]["windows"]["total"]
    assert tw["goodput_tokens"] == 100
    assert tw["total_tokens"] == 140
    assert tw["goodput_ratio"] == round(100 / 140, 6)


def test_goodput_counter_exported_through_scope():
    scope = M.MetricsScope().child(dtpu_namespace="ns9")
    t = [0.0]
    acct = SloAccountant(clock=lambda: t[0], objective=0.99, metrics=scope)
    acct.record("m1", SPEC, ttft_s=0.1, itl_s=0.01, output_tokens=7)
    acct.record("m1", SPEC, ttft_s=9.9, output_tokens=3)  # violated: no goodput
    acct.export_metrics()
    text = scope.expose().decode()
    good = next(
        l for l in text.splitlines()
        if l.startswith(M.GOODPUT_TOKENS + "{") or (
            l.startswith(M.GOODPUT_TOKENS) and "_total{" in l
        )
    )
    assert 'model="m1"' in good and 'sla_class="interactive"' in good
    assert good.rstrip().endswith("7.0")
    # attainment + burn gauges carry the full label hierarchy
    att = next(
        l for l in text.splitlines()
        if l.startswith(M.SLO_ATTAINMENT + "{") and 'window="total"' in l
        and 'slo="ttft"' in l
    )
    assert 'dtpu_namespace="ns9"' in att and 'sla_class="interactive"' in att
    assert att.rstrip().endswith("0.5")
    assert any(l.startswith(M.SLO_BURN_RATE + "{") for l in text.splitlines())


def test_debug_slo_payload_schema():
    t, acct = _clocked()
    acct.record("m", SPEC, ttft_s=0.1, itl_s=0.01, output_tokens=4)
    payload = slo.debug_slo_payload(acct)
    assert payload["windows"] == ["1h", "1m", "5m", "total"]
    body = payload["models"]["m"]["interactive"]
    assert body["targets"] == {
        "ttft_target_s": 0.5, "itl_target_s": 0.05, "deadline_s": 0.0,
    }
    for w in ("1m", "5m", "1h", "total"):
        win = body["windows"][w]
        assert {
            "requests", "ttft_attainment", "itl_attainment", "attainment",
            "burn_rate", "goodput_tokens", "total_tokens", "goodput_ratio",
        } <= set(win)
    assert slo.debug_slo_payload(None)["models"] == {}


async def test_status_server_serves_debug_slo():
    acct = SloAccountant()
    acct.record("worker-model", SPEC, ttft_s=0.2, output_tokens=2)
    set_slo_accountant(acct)
    server = StatusServer(HealthState(), host="127.0.0.1")
    await server.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{server.port}/debug/slo"
            ) as r:
                assert r.status == 200
                body = await r.json()
        assert "worker-model" in body["models"]
        win = body["models"]["worker-model"]["interactive"]["windows"]["total"]
        assert win["requests"] == 1 and win["attainment"] == 1.0
    finally:
        await server.stop()
        set_slo_accountant(None)


# ------------------------------------------------------ budget breakdown
def test_flight_budget_breakdown_and_debug_requests_section():
    rec = FlightRecorder(capacity=8)
    rec.record("r1", "queued", prompt_tokens=10, sla_class="interactive",
               ttft_target_s=1.0, itl_target_s=0.05, deadline_s=30.0)
    rec.record("r1", "admitted")
    rec.record("r1", "first_token")
    rec.finish("r1", status="200")
    flight = rec.timeline("r1")
    bb = budget_breakdown(flight)
    assert bb is not None and bb["sla_class"] == "interactive"
    assert {"queue_ms", "prefill_ms", "ttft_ms", "decode_ms"} <= set(bb)
    assert set(bb["budget_shares"]) == {"queue", "prefill"}
    assert bb["ttft_met"] is True
    assert "deadline_remaining_s" in bb
    # the ?id= payload carries the section; unclassified flights don't
    status, payload = debug_requests_payload(rec, "r1", None)
    assert status == 200 and payload["slo"]["sla_class"] == "interactive"
    rec.record("r2", "queued", prompt_tokens=1)
    rec.finish("r2", status="200")
    status, plain = debug_requests_payload(rec, "r2", None)
    assert status == 200 and "slo" not in plain


# ------------------------------------------------------ e2e: frontend -> engine
class _CaptureEngine(EchoEngine):
    """Echo worker that records the annotations it was dispatched with."""

    def __init__(self):
        super().__init__()
        self.seen = []

    async def generate(self, request, context):
        req = request if isinstance(request, dict) else None
        ann = (request.get("annotations") if isinstance(request, dict)
               else request.annotations)
        self.seen.append(ann or {})
        async for out in super().generate(request, context):
            yield out


async def test_sla_class_propagates_frontend_to_engine_e2e():
    """The acceptance e2e: a request tagged ``x-dtpu-sla: interactive``
    produces (a) the sla annotation on the worker side, (b) class-labeled
    TTFT/ITL histogram samples, (c) a populated /debug/slo payload, and
    (d) per-class attainment in the planner's LoadSnapshot."""
    store = MemKVStore()
    cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
    worker_rt = await DistributedRuntime(
        cfg, store=store, event_plane=InProcEventPlane()
    ).start()
    frontend_rt = await DistributedRuntime(
        cfg, store=store, event_plane=InProcEventPlane()
    ).start()
    engine = _CaptureEngine()
    card = ModelDeploymentCard(
        name="echo-model", tokenizer="byte", context_length=4096,
        # per-model override: interactive TTFT tightened on this card
        runtime_config=ModelRuntimeConfig(
            sla_classes={"interactive": {"ttft_target_s": 0.4}}
        ),
    )
    served = await register_llm(worker_rt, engine, card)
    manager = ModelManager()
    watcher = await ModelWatcher(
        frontend_rt, manager, RouterMode.ROUND_ROBIN
    ).start()
    # planner feed: frontend stats topic -> metrics source -> LoadSnapshot
    plane = frontend_rt.event_plane
    stats = FrontendStatsPublisher(plane, "dynamo")
    source = await EventPlaneMetricsSource(plane, "dynamo", ["backend"]).start()
    service = HttpService(
        manager, host="127.0.0.1", port=0, stats_hook=stats.on_request
    )
    await service.start()
    try:
        for _ in range(100):
            pipe = manager.get("echo-model")
            if pipe and pipe.client.instances:
                break
            await asyncio.sleep(0.05)
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/completions",
                json={"model": "echo-model", "prompt": "hello world",
                      "max_tokens": 8},
                headers={"x-dtpu-sla": "interactive"},
            )
            assert r.status == 200, await r.text()
            # (a) the worker saw the promise (class + overridden target)
            ann = next(a for a in engine.seen if slo.ANNOTATION_SLA in a)
            spec = spec_from_annotations(ann)
            assert spec.sla_class == "interactive"
            assert spec.ttft_target_s == 0.4  # model-card override applied
            assert slo.sla_t0_ns(ann) is not None
            # (b) class-labeled histogram samples on /metrics
            async with s.get(f"{base}/metrics") as mr:
                text = await mr.text()
            ttft_line = next(
                l for l in text.splitlines()
                if l.startswith(M.TTFT_SECONDS + "_count{")
            )
            assert 'sla_class="interactive"' in ttft_line
            assert 'model="echo-model"' in ttft_line
            itl_count = next(
                l for l in text.splitlines()
                if l.startswith(M.ITL_SECONDS + "_count{")
            )
            assert 'sla_class="interactive"' in itl_count
            dur = next(
                l for l in text.splitlines()
                if l.startswith(M.REQUEST_DURATION_SECONDS + "_count{")
            )
            assert 'sla_class="interactive"' in dur
            # (c) populated /debug/slo
            async with s.get(f"{base}/debug/slo") as dr:
                payload = await dr.json()
            win = payload["models"]["echo-model"]["interactive"]["windows"]
            assert win["total"]["requests"] == 1
            assert win["total"]["attainment"] in (0.0, 1.0)
            # body field beats the header; unknown class is a 400
            r2 = await s.post(
                f"{base}/v1/completions",
                json={"model": "echo-model", "prompt": "x", "max_tokens": 4,
                      "sla": "batch"},
                headers={"x-dtpu-sla": "interactive"},
            )
            assert r2.status == 200
            ann2 = spec_from_annotations(engine.seen[-1])
            assert ann2.sla_class == "batch"
            r3 = await s.post(
                f"{base}/v1/completions",
                json={"model": "echo-model", "prompt": "x", "sla": "nope"},
            )
            assert r3.status == 400
            body = await r3.json()
            assert "unknown SLA class" in body["error"]["message"]
        # (d) the planner snapshot carries per-class attainment
        for _ in range(50):
            await asyncio.sleep(0.02)
            snap = source.snapshot()
            if snap.class_attainment:
                break
        assert "interactive" in snap.class_attainment or (
            "batch" in snap.class_attainment
        )
        for v in snap.class_attainment.values():
            assert 0.0 <= v <= 1.0
    finally:
        source.stop()
        await service.stop()
        await watcher.stop()
        await served.stop()
        await worker_rt.shutdown()
        await frontend_rt.shutdown()


# ------------------------------------------------------ engine-side ledger
async def test_engine_feeds_global_accountant_and_violation_event():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.parallel.mesh import make_mesh
    from dynamo_tpu.runtime.engine import Context

    rec = FlightRecorder(capacity=16)
    set_flight_recorder(rec)
    acct = SloAccountant()
    set_slo_accountant(acct)
    mcfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
    )
    engine = TpuEngine(
        TpuEngineConfig(
            model=mcfg, num_blocks=64, block_size=4, max_batch_size=4,
            max_context=256, prefill_buckets=(16, 32, 64),
        ),
        mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
    )
    try:
        # impossible target -> violation; generous target -> met
        for rid, ttft_target in (("slo-viol", 1e-9), ("slo-ok", 60.0)):
            spec = SlaSpec("interactive", ttft_target, 60.0)
            req = PreprocessedRequest(
                request_id=rid, model="tiny", token_ids=list(range(40, 52)),
                stop=StopConditions(max_tokens=4, ignore_eos=True),
                sampling=SamplingOptions(temperature=0.0),
                annotations={slo.ANNOTATION_SLA: spec.to_annotation()},
            )
            async for _ in engine.generate(req, Context(rid)):
                pass
        assert acct.attainment("tiny", "interactive", "total", "ttft") == 0.5
        viol = rec.timeline("slo-viol")
        kinds = [e["event"]["kind"] for e in viol["events"]]
        assert "slo_violation" in kinds
        ev = next(
            e["event"] for e in viol["events"]
            if e["event"]["kind"] == "slo_violation"
        )
        assert ev["sla_class"] == "interactive" and ev["met"] is False
        # queued event carries the promise -> ?id= budget breakdown works
        status, payload = debug_requests_payload(rec, "slo-ok", None)
        assert status == 200 and payload["slo"]["ttft_met"] is True
        ok_kinds = [
            e["event"]["kind"] for e in rec.timeline("slo-ok")["events"]
        ]
        assert "slo_violation" not in ok_kinds
        # finish event is class-stamped
        fin = next(
            e["event"] for e in rec.timeline("slo-ok")["events"]
            if e["event"]["kind"] == "finish"
        )
        assert fin["sla_class"] == "interactive"
    finally:
        engine.stop()
        set_flight_recorder(None)
        set_slo_accountant(None)


# ------------------------------------------------------ dedupe pins
def test_loadgen_attainment_json_byte_identical():
    """sla_report_obj through runtime/slo.attainment must produce byte-
    identical JSON to the historical inline expressions."""
    from dynamo_tpu.profiler.loadgen import SlaReport, pct, sla_report_obj

    ttfts = [0.1, 0.2, 0.7, 0.05, 1.3]
    itls = [0.01, 0.09, 0.02]
    ttft_t, itl_t = 0.5, 0.05
    rep = SlaReport(
        completed=5,
        ttft_attainment=attainment(ttfts, ttft_t),
        itl_attainment=attainment(itls, itl_t),
        ttft_p95_s=pct(ttfts, 0.95),
        itl_p95_s=pct(itls, 0.95),
        cache_hit_ratio=0.25,
        sim_busy_s=1.0,
    )
    got = json.dumps(sla_report_obj(rep, workers=4))
    legacy_obj = {
        "requests": 5,
        "workers": 4,
        "ttft_attainment": round(
            sum(1 for x in ttfts if x <= ttft_t) / max(len(ttfts), 1), 4
        ),
        "itl_attainment": round(
            sum(1 for x in itls if x <= itl_t) / max(len(itls), 1), 4
        ),
        "ttft_p95_s": round(pct(ttfts, 0.95), 4),
        "itl_p95_s": round(pct(itls, 0.95), 4),
        "cache_hit_ratio": 0.25,
    }
    assert got == json.dumps(legacy_obj)
    # empty-list convention preserved (0.0, not 1.0)
    assert attainment([], 1.0) == 0.0


async def test_replay_uses_shared_attainment_helper():
    from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
    from dynamo_tpu.profiler import loadgen

    trace = loadgen.poisson_trace(6, rate=50.0, isl=32, osl=4)
    engines = [MockerEngine(MockEngineArgs(
        emit_sim_ts=True, speedup_ratio=50.0,
    ))]
    try:
        rep = await loadgen.replay(trace, engines, 10.0, 10.0, speedup=50.0)
    finally:
        for e in engines:
            e.stop()
    assert rep.completed == 6
    assert rep.ttft_attainment == 1.0 and rep.itl_attainment == 1.0


# ------------------------------------------------------ bench detail.slo
def test_bench_slo_detail_schema():
    """The record bench.py emits as detail.slo: per-class attainment +
    burn rate at the measured shapes (tier-1 schema pin alongside the
    detail.step_telemetry / detail.kernel_bytes checks)."""
    samples = [(0.1, 0.01, 64), (0.4, 0.02, 64), (3.0, 0.3, 64)]
    detail = bench_slo_detail(samples)
    assert detail["requests"] == 3
    assert {"interactive", "standard", "batch"} <= set(detail["classes"])
    inter = detail["classes"]["interactive"]
    assert {
        "ttft_target_s", "itl_target_s", "ttft_attainment", "itl_attainment",
        "attainment", "burn_rate", "goodput_tokens", "total_tokens",
    } <= set(inter)
    # tighter class -> no better attainment than the loosest class
    assert inter["attainment"] <= detail["classes"]["batch"]["attainment"]
    assert inter["total_tokens"] == 192
    # deterministic given the samples
    assert bench_slo_detail(samples) == detail


# ------------------------------------------------------ sim agreement smoke
def test_sim_mixed_sla_accountant_agrees_with_trace():
    """The production accountant on the virtual clock must reproduce the
    trace-derived attainment exactly (multi-pool mixed-SLA scenario) and
    its ledger lands in the deterministic report."""
    from dynamo_tpu.sim.scenarios import run_scenario

    r = run_scenario("multi-pool-balance", seed=3, workers=6, duration_s=120)
    inv = next(
        iv for iv in r["sim"]["invariants"]
        if iv["name"] == "mixed_sla_classes_accounted"
    )
    assert inv["ok"], inv["detail"]
    assert r["sim"]["passed"]
    slo_sec = r["sim"]["pools"]["interactive"]["slo"]
    assert slo_sec["objective"] == 0.99
    inter = slo_sec["classes"]["interactive"]
    assert inter["windows"]["total"]["requests"] > 0
    assert (
        inter["windows"]["total"]["ttft_attainment"]
        == r["sim"]["pools"]["interactive"]["ttft_attainment"]
    )


# ------------------------------------------------------ review-fix pins
def test_default_class_typo_falls_back_not_400(monkeypatch):
    monkeypatch.setenv("DTPU_SLA_DEFAULT", "interctive")  # typo'd default
    spec = resolve_sla(None)
    assert spec is not None and spec.sla_class == "standard"
    # an EXPLICITLY named unknown class still resolves to None (-> 400)
    assert resolve_sla("interctive") is None


def test_export_metrics_neutralizes_drained_windows():
    scope = M.MetricsScope()
    t = [0.0]
    acct = SloAccountant(clock=lambda: t[0], objective=0.99, metrics=scope)
    for _ in range(5):  # violation burst at t=0: 1m burn rate = 100
        acct.record("m", SPEC, ttft_s=9.0, output_tokens=1)
    acct.export_metrics()

    def burn_1m():
        line = next(
            l for l in scope.expose().decode().splitlines()
            if l.startswith(M.SLO_BURN_RATE + "{") and 'window="1m"' in l
        )
        return float(line.rsplit(" ", 1)[1])

    assert abs(burn_1m() - 100.0) < 1e-6
    t[0] = 600.0  # traffic stops; the 1m window drains
    acct.export_metrics()
    assert burn_1m() == 0.0  # not frozen at the stale page-now value


def test_bench_slo_detail_scores_deadline_classes(monkeypatch):
    monkeypatch.setenv("DTPU_SLA_CLASSES", "rt:ttft=5,itl=1,deadline=30")
    detail = bench_slo_detail([(0.1, 0.01, 16), (0.2, 0.02, 16)])
    rt = detail["classes"]["rt"]
    # fast samples within the deadline must not auto-miss on e2e
    assert rt["attainment"] == 1.0 and rt["burn_rate"] == 0.0


def test_planner_class_outcome_honors_accountant_verdict():
    plane = InProcEventPlane()
    src = EventPlaneMetricsSource(plane, "ns", [])
    # latencies meet both targets, but the publisher's accountant said the
    # request missed (e.g. blew its deadline): the verdict wins
    src.record_class_outcome(
        "interactive", ttft_s=0.1, ttft_target_s=1.0,
        itl_s=0.01, itl_target_s=0.1, met=False,
    )
    src.record_class_outcome(
        "interactive", ttft_s=0.1, ttft_target_s=1.0,
        itl_s=0.01, itl_target_s=0.1,  # no verdict -> local derivation
    )
    snap = src.snapshot()
    assert snap.class_attainment == {"interactive": 0.5}


async def test_failure_before_first_token_lands_in_frontend_ledger():
    from aiohttp.test_utils import make_mocked_request

    from dynamo_tpu.runtime.request_plane.tcp import NoResponders

    class _DeadPipeline:
        async def generate_tokens(self, preq, ctx):
            raise NoResponders("nobody home")
            yield  # pragma: no cover

    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    spec = SlaSpec("interactive", 0.5, 0.05)
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest
    from dynamo_tpu.llm.protocols.delta import (
        CompletionDeltaGenerator,
        aggregate_completion,
    )

    preq = PreprocessedRequest(
        request_id="dead-1", model="m", token_ids=[1, 2, 3]
    )
    req = make_mocked_request("POST", "/v1/completions")
    resp = await service._run(
        req, [preq], _DeadPipeline(), "m", False,
        [CompletionDeltaGenerator("dead-1", "m", False)],
        lambda ss: aggregate_completion("dead-1", "m", ss[0], ""),
        sla=spec,
    )
    assert resp.status == 503
    win = service.slo.snapshot()["models"]["m"]["interactive"]["windows"]
    # the outage IS accounted: a combined miss with no ttft sample
    assert win["total"]["requests"] == 1
    assert win["total"]["attainment"] == 0.0
    assert win["total"]["ttft_attainment"] is None
