"""Multi-choice (n>1) chat/completions: per-choice streams, jail, usage.

Reference parity: the delta generator and jail operate per-choice
(lib/llm/src/protocols/openai/chat_completions/{delta,jail}.rs); n>1 fans one
request into n engine streams folded into indexed choices.
"""

import json

import aiohttp

from dynamo_tpu.llm import (
    EchoEngine,
    ModelDeploymentCard,
    ModelManager,
    ModelWatcher,
    register_llm,
)
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.runtime import (
    DistributedRuntime,
    InProcEventPlane,
    MemKVStore,
    RouterMode,
    RuntimeConfig,
)
import asyncio


def make_rt(store):
    cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
    return DistributedRuntime(cfg, store=store, event_plane=InProcEventPlane())


async def start_stack(card=None):
    store = MemKVStore()
    worker_rt = await make_rt(store).start()
    frontend_rt = await make_rt(store).start()
    card = card or ModelDeploymentCard(
        name="echo-model", tokenizer="byte", context_length=4096
    )
    served = await register_llm(worker_rt, EchoEngine(), card)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    for _ in range(100):
        p = manager.get(card.name)
        if p and p.client.instances:
            break
        await asyncio.sleep(0.05)
    handles = (worker_rt, frontend_rt, served, watcher, service)
    return handles, f"http://127.0.0.1:{service.port}", card.name


async def stop_stack(worker_rt, frontend_rt, served, watcher, service):
    await service.stop()
    await watcher.stop()
    await served.stop()
    await worker_rt.shutdown()
    await frontend_rt.shutdown()


async def _sse_chunks(resp):
    chunks = []
    done = 0
    async for raw in resp.content:
        line = raw.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            done += 1
            continue
        chunks.append(json.loads(payload))
    return chunks, done


async def test_chat_n2_aggregated():
    handles, base, model = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/chat/completions",
                json={
                    "model": model, "n": 2,
                    "messages": [{"role": "user", "content": "fanout"}],
                },
            )
            assert r.status == 200, await r.text()
            body = await r.json()
        choices = body["choices"]
        assert [c["index"] for c in choices] == [0, 1]
        for c in choices:
            assert "fanout" in c["message"]["content"]
        # prompt billed once, completion summed across choices
        per_choice = body["usage"]["completion_tokens"] // 2
        assert body["usage"]["completion_tokens"] == 2 * per_choice > 0
        assert body["usage"]["total_tokens"] == (
            body["usage"]["prompt_tokens"] + body["usage"]["completion_tokens"]
        )
    finally:
        await stop_stack(*handles)


async def test_chat_n3_streaming_interleaves_and_merges_usage():
    handles, base, model = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/chat/completions",
                json={
                    "model": model, "n": 3, "stream": True,
                    "stream_options": {"include_usage": True},
                    "messages": [{"role": "user", "content": "abc"}],
                },
            )
            assert r.status == 200, await r.text()
            chunks, done = await _sse_chunks(r)
        assert done == 1
        seen = {}
        finishes = set()
        usage_chunks = [c for c in chunks if not c["choices"] and c.get("usage")]
        for c in chunks:
            for ch in c["choices"]:
                i = ch["index"]
                seen.setdefault(i, []).append(ch["delta"].get("content") or "")
                if ch.get("finish_reason"):
                    finishes.add(i)
        assert set(seen) == {0, 1, 2}
        assert finishes == {0, 1, 2}
        texts = {i: "".join(parts) for i, parts in seen.items()}
        for i in range(3):
            assert "abc" in texts[i]
        # exactly one merged usage chunk covering all choices
        assert len(usage_chunks) == 1
        u = usage_chunks[0]["usage"]
        per = u["completion_tokens"] // 3
        assert u["completion_tokens"] == 3 * per > 0
        # all chunks share one response id
        assert len({c["id"] for c in chunks}) == 1
    finally:
        await stop_stack(*handles)


async def test_chat_n2_streaming_tool_call_per_choice_jail():
    """Each choice runs its own tool parser/jail: a tool-call in the stream
    must come out as a parsed tool_calls delta on BOTH choice indexes with
    no cross-choice state bleed."""
    card = ModelDeploymentCard(
        name="tool-echo", tokenizer="byte", context_length=4096,
        tool_parser="hermes",
    )
    handles, base, model = await start_stack(card)
    payload = '<tool_call>{"name": "get_w", "arguments": {"city": "SF"}}</tool_call>'
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/chat/completions",
                json={
                    "model": model, "n": 2, "stream": True,
                    "messages": [{"role": "user", "content": payload}],
                },
            )
            assert r.status == 200, await r.text()
            chunks, _ = await _sse_chunks(r)
        calls = {0: [], 1: []}
        finishes = {}
        for c in chunks:
            for ch in c["choices"]:
                if ch["delta"].get("tool_calls"):
                    calls[ch["index"]].extend(ch["delta"]["tool_calls"])
                if ch.get("finish_reason"):
                    finishes[ch["index"]] = ch["finish_reason"]
        for i in (0, 1):
            assert len(calls[i]) == 1, (i, calls)
            assert calls[i][0]["function"]["name"] == "get_w"
            assert json.loads(calls[i][0]["function"]["arguments"]) == {"city": "SF"}
            # per-choice tool-call indexes restart at 0
            assert calls[i][0]["index"] == 0
            assert finishes[i] == "tool_calls"
    finally:
        await stop_stack(*handles)


async def test_completions_n2_aggregated():
    handles, base, model = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/completions",
                json={"model": model, "prompt": "hello", "n": 2},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
        assert [c["index"] for c in body["choices"]] == [0, 1]
        for c in body["choices"]:
            assert "hello" in c["text"]
        per = body["usage"]["completion_tokens"] // 2
        assert body["usage"]["completion_tokens"] == 2 * per > 0
    finally:
        await stop_stack(*handles)


async def test_chat_n_cap_enforced():
    handles, base, model = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"{base}/v1/chat/completions",
                json={
                    "model": model, "n": 64,
                    "messages": [{"role": "user", "content": "x"}],
                },
            )
            assert r.status == 400
    finally:
        await stop_stack(*handles)
