"""Test harness config.

All JAX tests run on a virtual 8-device CPU mesh (the multi-chip sharding path
is validated without TPU hardware, mirroring the reference's mocker-based
GPU-free test strategy, reference tests/README.md). Set env BEFORE jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # env exports axon (real TPU); tests force CPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # XLA's parallel LLVM codegen intermittently SIGABRTs mid-compile on
    # this image (~50% per multi-engine session; one abort kills the whole
    # pytest process). Serial codegen is rock-stable (measured 0 crashes)
    # and the compile-time cost is amortized by the persistent cache.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags
# The persistent compile cache is DISABLED for tests: on this image the
# cache's native load/store path segfaults or aborts the whole pytest
# process (measured: test_guided crashed at the same test 8/8 runs with a
# warm cache and passed 18/18 tests with the cache off; same for the
# chunked-prefill engine tests). Recompiling costs ~30-60s per engine-heavy
# file; a single segfault costs every test after it in the session.
os.environ["JAX_COMPILATION_CACHE_DIR"] = ""
# Mixed continuous batching (engine mixed_step) compiles ONE extra fused
# program the first time a prefill overlaps resident decodes; across the
# suite's dozens of tiny engines that is minutes of serial XLA compile for a
# path tests/test_mixed_batching.py pins explicitly (engines there opt in
# via TpuEngineConfig(mixed_admission=True)). Default off for the suite;
# setdefault so DTPU_MIXED=1 can still force it everywhere.
os.environ.setdefault("DTPU_MIXED", "0")

import jax  # noqa: E402

# the axon TPU plugin pins itself regardless of the env var; the config update
# is what actually forces the CPU backend with the 8 virtual devices
jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy tests (multi-engine spec-decode builds) excluded "
        "from the tier-1 run (-m 'not slow'); run them serially via "
        "-m slow — they time out under parallel/xdist runs on this image",
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests on a fresh event loop (no pytest-asyncio here)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None


@pytest.fixture
def tmp_store_path(tmp_path):
    return str(tmp_path / "store")


@pytest.fixture(scope="session")
def repo_analysis():
    """ONE whole-tree tools/analysis run (dynamo_tpu/, every pass, no
    baseline) shared by every current-tree pin in test_analysis.py /
    test_analysis_flows.py — each used to reload and re-analyze the tree
    themselves, which multiplied ~7s per test into the tier-1 clock.
    Returns (modules, parse_findings, findings)."""
    from tools.analysis import core

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    modules, parse = core.load_modules([os.path.join(repo, "dynamo_tpu")])
    findings = core.collect_findings(modules, parse)
    return modules, parse, findings


@pytest.fixture(scope="session")
def repo_analysis_full():
    """ONE run over the FULL gated tree (dynamo_tpu/ + tools/ + tests/) for
    the cross-plane contract pins: the contract spec table registers
    consumer sites that live under tests/ (the /debug/requests schema
    pins), so the dynamo_tpu-only ``repo_analysis`` view would report
    direction drift a full run doesn't. Returns (modules, parse,
    findings)."""
    from tools.analysis import core

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    modules, parse = core.load_modules(
        [os.path.join(repo, p) for p in ("dynamo_tpu", "tools", "tests")]
    )
    findings = core.collect_findings(modules, parse)
    return modules, parse, findings
