"""Test harness config.

All JAX tests run on a virtual 8-device CPU mesh (the multi-chip sharding path
is validated without TPU hardware, mirroring the reference's mocker-based
GPU-free test strategy, reference tests/README.md). Set env BEFORE jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # env exports axon (real TPU); tests force CPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# persistent compile cache: engine tests compile several XLA programs
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dtpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

import jax  # noqa: E402

# the axon TPU plugin pins itself regardless of the env var; the config update
# is what actually forces the CPU backend with the 8 virtual devices
jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests on a fresh event loop (no pytest-asyncio here)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None


@pytest.fixture
def tmp_store_path(tmp_path):
    return str(tmp_path / "store")
