"""Migration operator unit tests (llm/migration.py).

Reference analog: lib/llm/src/migration.rs:24-43 — replay in-flight requests
to another worker on transport loss, carrying generated tokens forward.
"""

import pytest

from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.request_plane.tcp import NoResponders


def _req(max_tokens=8):
    return PreprocessedRequest(
        request_id="r1", model="m", token_ids=[1, 2, 3],
        stop=StopConditions(max_tokens=max_tokens),
        sampling=SamplingOptions(),
    )


class _FlakySend:
    """First call streams 3 tokens then dies; later calls finish the rest."""

    def __init__(self, exc):
        self.exc = exc
        self.calls = []  # (excluded_snapshot, prior_token_ids)

    async def __call__(self, req, context, excluded):
        self.calls.append((list(excluded), list(req.prior_token_ids),
                           req.stop.max_tokens))

        async def first():
            for t in (10, 11, 12):
                yield BackendOutput(token_ids=[t], cumulative_tokens=1)
            raise self.exc

        async def rest():
            n = len(req.prior_token_ids)
            for t in range(20, 20 + (8 - n)):
                yield BackendOutput(token_ids=[t], cumulative_tokens=1)
            yield BackendOutput(finish_reason="length", cumulative_tokens=0)

        return first() if len(self.calls) == 1 else rest()


async def _collect(migration, req):
    toks = []
    async for out in migration.generate(req, Context()):
        toks.extend(out.token_ids)
    return toks


async def test_migrates_on_tagged_connection_error():
    """A mid-stream ConnectionError carrying instance_id excludes that worker
    on the retry — the round-3 verdict's exclusion gap."""
    exc = ConnectionError("connection lost")
    exc.instance_id = 0xDEAD
    send = _FlakySend(exc)
    toks = await _collect(Migration(send, migration_limit=2), _req())
    assert len(send.calls) == 2
    # retry excluded the dead worker and replayed progress
    assert send.calls[1][0] == [0xDEAD]
    assert send.calls[1][1] == [10, 11, 12]
    # max_tokens shrank by the tokens already delivered
    assert send.calls[1][2] == 8 - 3
    assert toks[:3] == [10, 11, 12] and len(toks) == 8


async def test_migrates_on_no_responders():
    exc = NoResponders("gone")
    exc.instance_id = 7
    send = _FlakySend(exc)
    toks = await _collect(Migration(send, migration_limit=1), _req())
    assert send.calls[1][0] == [7]
    assert len(toks) == 8


async def test_limit_zero_raises():
    send = _FlakySend(ConnectionError("connection lost"))
    with pytest.raises(ConnectionError):
        await _collect(Migration(send, migration_limit=0), _req())
    assert len(send.calls) == 1
