"""Disaggregated prefill/decode e2e on the virtual CPU mesh.

Golden correctness: the disaggregated path (prefill worker -> KV transfer ->
decode worker) must produce token-identical greedy output to the aggregated
path, with the decode worker importing (not recomputing) the prefill KV.
"""

import pytest

import asyncio

import jax.numpy as jnp

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.llm import ModelDeploymentCard, ModelManager, ModelWatcher, register_llm
from dynamo_tpu.llm.model_card import MODEL_TYPE_PREFILL
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.runtime import (
    Context,
    DistributedRuntime,
    InProcEventPlane,
    MemKVStore,
    RouterMode,
    RuntimeConfig,
)
from dynamo_tpu.tokens import compute_sequence_hashes


def tiny_cfg(model=None, **kw):
    mcfg = model or LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
    )
    defaults = dict(
        num_blocks=64, block_size=4, max_batch_size=4, max_context=128,
        prefill_buckets=(16, 32, 64, 128),
    )
    defaults.update(kw)
    return TpuEngineConfig(model=mcfg, **defaults)


def make_rt(store, plane):
    cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
    return DistributedRuntime(cfg, store=store, event_plane=plane)


def preq(rid, tokens, max_tokens=8):
    return PreprocessedRequest(
        request_id=rid, model="disagg-model", token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def test_disagg_matches_aggregated(monkeypatch):
    # the 30-token prompt is below the deflection threshold; pin deflection
    # off — this test is about the transfer path itself
    monkeypatch.setenv("DTPU_DEFLECT", "0")
    await _disagg_matches_aggregated()


async def test_disagg_matches_aggregated_sequential(monkeypatch):
    """Legacy sequential pipeline (DTPU_STREAM_KV=0): prefill completes,
    first token streams from the prefill worker, the decode hop pulls the
    whole KV blocking-style. Must stay byte-identical to aggregated."""
    monkeypatch.setenv("DTPU_DEFLECT", "0")
    monkeypatch.setenv("DTPU_STREAM_KV", "0")
    await _disagg_matches_aggregated()


@pytest.mark.slow
async def test_disagg_matches_aggregated_gptoss(monkeypatch):
    """Disaggregated prefill/decode with gpt-oss: the transferred KV pages
    carry windowed+sink attention context; the decode engine's import must
    reproduce the aggregated greedy output exactly."""
    from dynamo_tpu.models.gptoss import GptOssConfig

    monkeypatch.setenv("DTPU_DEFLECT", "0")
    await _disagg_matches_aggregated(mcfg=GptOssConfig.tiny_gptoss())


async def test_disagg_short_prompt_deflects(monkeypatch):
    """Prefill deflection: a short prompt skips the disagg hop entirely —
    the decode worker prefills locally (no transferred blocks), output
    still correct, and the flight recorder shows the deflection."""
    monkeypatch.setenv("DTPU_DEFLECT", "1")
    monkeypatch.setenv("DTPU_DEFLECT_MAX_TOKENS", "64")
    from dynamo_tpu.runtime.flight_recorder import get_flight_recorder

    prompt = list(range(100, 130))  # 30 tokens <= 64: deflects

    agg = TpuEngine(tiny_cfg())
    golden = []
    try:
        async for out in agg.generate(preq("golden-defl", prompt), Context()):
            golden.extend(out.token_ids)
    finally:
        agg.stop()

    store = MemKVStore()
    plane = InProcEventPlane()
    prefill_rt = await make_rt(store, plane).start()
    decode_rt = await make_rt(store, plane).start()
    frontend_rt = await make_rt(store, plane).start()
    prefill_engine = TpuEngine(tiny_cfg())
    await prefill_engine.serve_transfer()
    decode_engine = TpuEngine(tiny_cfg())
    prefill_card = ModelDeploymentCard(
        name="disagg-model", component="backend_prefill",
        model_type=[MODEL_TYPE_PREFILL], tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    decode_card = ModelDeploymentCard(
        name="disagg-model", component="backend", tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    s_prefill = await register_llm(prefill_rt, prefill_engine, prefill_card)
    s_decode = await register_llm(decode_rt, decode_engine, decode_card)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    try:
        for _ in range(100):
            pipe = manager.get("disagg-model")
            if (
                pipe is not None and pipe.client.instances
                and pipe.prefill_router is not None
                and pipe.prefill_router.has_workers
            ):
                break
            await asyncio.sleep(0.05)
        pipe = manager.get("disagg-model")
        got = []
        async for out in pipe.generate_tokens(preq("defl", prompt), Context()):
            got.extend(out.token_ids)
        assert got == golden
        # deflected: nothing was transferred into the decode allocator from
        # the prefill engine, and the prefill engine never saw the request
        flight = get_flight_recorder().timeline("defl") or {"events": []}
        kinds = [e["event"]["kind"] for e in flight["events"]]
        assert "prefill_deflected" in kinds, kinds
        # the prefill pool never prefilled this prompt
        hashes = compute_sequence_hashes(prompt, 4)
        assert prefill_engine.allocator.match_prefix(hashes[:7]) == []
    finally:
        await watcher.stop()
        await s_prefill.stop()
        await s_decode.stop()
        prefill_engine.stop()
        decode_engine.stop()
        await prefill_rt.shutdown()
        await decode_rt.shutdown()
        await frontend_rt.shutdown()


async def _disagg_matches_aggregated(mcfg=None):
    prompt = list(range(100, 130))  # 30 tokens

    # ---- golden: aggregated single engine ----
    agg = TpuEngine(tiny_cfg(model=mcfg))
    golden = []
    try:
        async for out in agg.generate(preq("golden", prompt), Context()):
            golden.extend(out.token_ids)
    finally:
        agg.stop()
    assert len(golden) == 8

    # ---- disaggregated stack ----
    store = MemKVStore()
    plane = InProcEventPlane()
    prefill_rt = await make_rt(store, plane).start()
    decode_rt = await make_rt(store, plane).start()
    frontend_rt = await make_rt(store, plane).start()

    prefill_engine = TpuEngine(tiny_cfg(model=mcfg))
    await prefill_engine.serve_transfer()
    decode_engine = TpuEngine(tiny_cfg(model=mcfg))

    prefill_card = ModelDeploymentCard(
        name="disagg-model", component="backend_prefill",
        model_type=[MODEL_TYPE_PREFILL], tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    decode_card = ModelDeploymentCard(
        name="disagg-model", component="backend", tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    s_prefill = await register_llm(prefill_rt, prefill_engine, prefill_card)
    s_decode = await register_llm(decode_rt, decode_engine, decode_card)

    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    try:
        for _ in range(100):
            pipe = manager.get("disagg-model")
            if (
                pipe is not None
                and pipe.client.instances
                and pipe.prefill_router is not None
                and pipe.prefill_router.has_workers
            ):
                break
            await asyncio.sleep(0.05)
        pipe = manager.get("disagg-model")
        assert pipe is not None and pipe.prefill_router is not None

        got = []
        cum = []
        async for out in pipe.generate_tokens(preq("disagg", prompt), Context()):
            got.extend(out.token_ids)
            cum.append(out.cumulative_tokens)
        assert got == golden, f"disagg {got} != aggregated {golden}"
        assert cum[-1] == len(golden)

        # the decode engine must have IMPORTED the prefill pages: its
        # allocator should know the prompt's complete-block hashes
        hashes = compute_sequence_hashes(prompt, 4)
        reusable = (len(prompt) - 1) // 4
        matched = decode_engine.allocator.match_prefix(hashes[:reusable])
        assert len(matched) > 0, "no transferred blocks in decode allocator"
    finally:
        await watcher.stop()
        await s_prefill.stop()
        await s_decode.stop()
        prefill_engine.stop()
        decode_engine.stop()
        await prefill_rt.shutdown()
        await decode_rt.shutdown()
        await frontend_rt.shutdown()


async def test_disagg_falls_back_without_prefill_pool():
    """Elastic xPyD: no prefill workers -> aggregated path serves unchanged."""
    store = MemKVStore()
    plane = InProcEventPlane()
    decode_rt = await make_rt(store, plane).start()
    frontend_rt = await make_rt(store, plane).start()
    engine = TpuEngine(tiny_cfg())
    card = ModelDeploymentCard(
        name="disagg-model", component="backend", tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    served = await register_llm(decode_rt, engine, card)
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    try:
        for _ in range(100):
            pipe = manager.get("disagg-model")
            if pipe is not None and pipe.client.instances:
                break
            await asyncio.sleep(0.05)
        pipe = manager.get("disagg-model")
        assert pipe.prefill_router is None
        got = []
        async for out in pipe.generate_tokens(preq("agg", list(range(20))), Context()):
            got.extend(out.token_ids)
        assert len(got) == 8
    finally:
        await watcher.stop()
        await served.stop()
        engine.stop()
        await decode_rt.shutdown()
        await frontend_rt.shutdown()


async def test_prefill_terminal_error_surfaces_instead_of_fallback():
    """A typed 4xx-class failure from the prefill pool (context length,
    guided grammar, ...) must propagate to the client: the request itself is
    wrong, so the aggregated fallback would only re-run the same doomed
    prefill. Transport-class failures still fall back (return None).

    Regression test for the broad ``except Exception -> return None`` in
    PrefillRouter.run_prefill that swallowed runtime/errors.py typed errors
    (flagged while building tools/analysis)."""
    import pytest

    from dynamo_tpu.llm.prefill_router import PrefillRouter
    from dynamo_tpu.runtime.request_plane.tcp import RequestPlaneError

    class StubClient:
        def __init__(self, exc):
            self.exc = exc
            self.instances = {1: object()}

        async def generate(self, obj, context, instance_id=None):
            raise self.exc

        async def stop(self):
            pass

    card = ModelDeploymentCard(
        name="disagg-model", component="prefill", tokenizer="byte",
        kv_block_size=4, context_length=128,
    )
    router = PrefillRouter(runtime=None, card=card)

    # worker-side typed error rides the wire as RequestPlaneError(code=...)
    router.client = StubClient(
        RequestPlaneError("prompt exceeds model context", code="context_length")
    )
    with pytest.raises(RequestPlaneError, match="context"):
        await router.run_prefill(preq("terminal", list(range(8))), Context())

    # transport-ish failure: fall back to aggregated (None), don't raise
    router.client = StubClient(RuntimeError("socket exploded"))
    out = await router.run_prefill(preq("transient", list(range(8))), Context())
    assert out is None


@pytest.mark.slow
async def test_disagg_uses_native_transfer(monkeypatch):
    """When the C++ agent is available, the KV bytes move over it (the
    request plane only carries slot metadata), and the decode side still
    imports rather than recomputes."""
    # co-resident engines would take the ICI device path; force the wire,
    # and pin the NATIVE protocol (the cross-process device plane outranks it)
    monkeypatch.setenv("DTPU_ICI_TRANSFER", "0")
    monkeypatch.setenv("DTPU_DEVICE_TRANSFER", "0")
    import dynamo_tpu.transfer as nt

    if not nt.native_available():
        import pytest
        pytest.skip("native toolchain unavailable")

    calls = []
    real_fetch = nt.native_fetch

    def counting_fetch(*a, **kw):
        calls.append(a)
        return real_fetch(*a, **kw)

    monkeypatch.setattr(nt, "native_fetch", counting_fetch)

    # bf16 caches: the arena + wire dtype follow the cache dtype (the
    # realistic config; exercises the ml_dtypes name round-trip)
    bf16_model = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128,
        dtype=jnp.bfloat16,
    )
    prefill = TpuEngine(tiny_cfg(model=bf16_model))
    decode = TpuEngine(tiny_cfg(model=bf16_model))
    try:
        addr = await prefill.serve_transfer()
        prompt = list(range(200, 240))  # 40 tokens = 10 blocks
        # aggregated reference on a third engine
        ref_engine = TpuEngine(tiny_cfg(model=bf16_model))
        try:
            ref = []
            async for out in ref_engine.generate(preq("ref", prompt), Context()):
                ref.extend(out.token_ids)
        finally:
            ref_engine.stop()

        # prefill side: run max_tokens=1 to populate its cache
        async for _ in prefill.generate(preq("p", prompt, max_tokens=1), Context()):
            pass
        hashes = [int(h) for h in compute_sequence_hashes(prompt, 4)]
        req = preq("d", prompt)
        req.kv_transfer = {"address": addr, "hashes": hashes}
        toks = []
        cached = None
        async for out in decode.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.annotations and "cached_tokens" in out.annotations:
                cached = out.annotations["cached_tokens"]
        assert calls, "native transfer path was not used"
        assert cached and cached > 0  # imported, not recomputed
        assert toks == ref
    finally:
        prefill.stop()
        decode.stop()


@pytest.mark.slow
async def test_stale_lease_overwrite_never_imports_torn_bytes(monkeypatch):
    monkeypatch.setenv("DTPU_ICI_TRANSFER", "0")  # wire-protocol test
    monkeypatch.setenv("DTPU_DEVICE_TRANSFER", "0")  # pin the native path
    """The slot-lease race (ADVICE r2): a fetch stalled past lease expiry
    whose slots were re-gathered for another request must NOT import the
    overwritten bytes — the gather-time checksums catch the tear and the
    decode side recomputes, keeping greedy output identical."""
    import numpy as np
    import pytest

    import dynamo_tpu.transfer as nt

    if not nt.native_available():
        pytest.skip("native toolchain unavailable")

    prefill = TpuEngine(tiny_cfg())
    decode = TpuEngine(tiny_cfg())
    overwrote = []
    real_fetch = nt.native_fetch

    def stalled_fetch(host, port, region, slots, block_bytes):
        # simulate: while this client is stalled, the lease expires and the
        # server re-gathers ANOTHER request into the same slots
        srv = prefill._kv_transfer_srv
        srv._arena.view(np.uint8)[np.asarray(slots)] ^= 0xFF  # torn bytes
        overwrote.append(list(slots))
        return real_fetch(host, port, region, slots, block_bytes)

    monkeypatch.setattr(nt, "native_fetch", stalled_fetch)
    try:
        addr = await prefill.serve_transfer()
        prompt = list(range(100, 140))  # 10 blocks
        ref_engine = TpuEngine(tiny_cfg())
        try:
            ref = []
            async for out in ref_engine.generate(preq("ref", prompt), Context()):
                ref.extend(out.token_ids)
        finally:
            ref_engine.stop()
        async for _ in prefill.generate(preq("p", prompt, max_tokens=1), Context()):
            pass
        hashes = [int(h) for h in compute_sequence_hashes(prompt, 4)]
        req = preq("d", prompt)
        req.kv_transfer = {"address": addr, "hashes": hashes}
        toks = []
        cached = None
        async for out in decode.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.annotations and "cached_tokens" in out.annotations:
                cached = out.annotations["cached_tokens"]
        assert overwrote, "native path not exercised"
        # torn bytes rejected: nothing imported, prefill recomputed locally
        assert not cached
        # and the output is still correct (no poisoned prefix cache)
        assert toks == ref
    finally:
        prefill.stop()
        decode.stop()
