"""Operator reconcile loop e2e: planner -> scale target -> controller ->
real worker processes -> discovery.

Round-3 verdict item #8: the controller (deploy/controller.py) is the
DynamoGraphDeployment-controller analog — it must actually reconcile:
spawn to spec, pick up planner scale targets, restart crashes, reap on
scale-down, and report status. Reference:
deploy/operator/internal/controller/dynamographdeployment_controller.go,
tests/planner/test_scaling_e2e.py.
"""

import asyncio
import os
import signal

from dynamo_tpu.deploy.controller import GraphController, default_runner, status_key
from dynamo_tpu.deploy.render import GraphSpec, ServiceSpec
from dynamo_tpu.planner.connectors import VirtualConnector
from dynamo_tpu.planner.core import (
    LoadSnapshot,
    PerfInterpolator,
    PlannerConfig,
    PoolPlanner,
)
from dynamo_tpu.runtime import DistributedRuntime, InProcEventPlane, RuntimeConfig
from dynamo_tpu.runtime.discovery.store import make_store


def _graph() -> GraphSpec:
    return GraphSpec(
        name="op-e2e",
        services=[ServiceSpec(
            name="backend", kind="worker", replicas=1,
            args=["--model", "op-model", "--event-plane", "inproc",
                  "--migration-limit", "0"],
        )],
    )


async def _wait(cond, timeout=60.0, every=0.2, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        v = cond()
        if asyncio.iscoroutine(v):
            v = await v
        if v:
            return
        await asyncio.sleep(every)
    raise AssertionError(f"timeout waiting for {msg}")


def test_planner_scales_through_controller(tmp_path):
    asyncio.run(asyncio.wait_for(_run(tmp_path), timeout=240))


async def _run(tmp_path):
    store_path = str(tmp_path / "store")
    env = {"JAX_PLATFORMS": "cpu"}
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    store = make_store("file", store_path)
    ctl = GraphController(
        store, _graph(), runner=default_runner("file", store_path),
        interval_s=0.3, restart_backoff_s=0.2, env=env,
    ).start()

    # a discovery-side client runtime, like a frontend would hold
    rt = await DistributedRuntime(
        RuntimeConfig(store="file", store_path=store_path,
                      event_plane="inproc", lease_ttl_s=2.0),
        event_plane=InProcEventPlane(),
    ).start()
    client = await rt.namespace("dynamo").component("backend").endpoint(
        "generate"
    ).client()
    try:
        # 1. spec replicas=1 -> one worker registers
        await _wait(lambda: len(client.instances) == 1, msg="first worker")

        # 2. the PLANNER raises the target: high observed load vs a profile
        #    that sustains 1000 t/s/worker -> 3 workers; controller obeys
        conn = VirtualConnector(store)
        interp = PerfInterpolator()
        interp.fit_prefill([(128.0, 1000.0)])
        pool = PoolPlanner(
            "prefill", "backend", conn,
            PlannerConfig(min_replicas=1, max_replicas=8),
            lambda s: interp.prefill_capacity(s.avg_isl),
        )
        for _ in range(5):
            pool.observe(2500.0)
        desired = await pool.plan_and_apply(LoadSnapshot(avg_isl=128.0))
        assert desired == 3
        await _wait(lambda: len(client.instances) == 3, msg="scale to 3")

        # 3. crash one worker: the controller restarts it (pod restart)
        victim = ctl._procs["backend"][0].popen
        victim.send_signal(signal.SIGKILL)
        await _wait(
            lambda: ctl.restarts_total >= 1
            and sum(
                1 for p in ctl._procs["backend"] if p.popen.poll() is None
            ) == 3,
            msg="crash restart",
        )

        # 4. scale down to 1: processes reaped, status reflects it
        await conn.set_replicas("backend", 1)
        await _wait(
            lambda: len([
                p for p in ctl._procs["backend"] if p.popen.poll() is None
            ]) == 1,
            msg="scale down",
        )
        status = await store.get_obj(status_key("dynamo", "op-e2e"))
        assert status and status["services"]["backend"]["desired"] == 1
    finally:
        await rt.shutdown()
        await ctl.stop()
        await store.close()


def test_spec_hot_reload(tmp_path):
    asyncio.run(asyncio.wait_for(_run_reload(tmp_path), timeout=120))


async def _run_reload(tmp_path):
    import yaml

    store_path = str(tmp_path / "store")
    spec_path = str(tmp_path / "graph.yaml")

    def write_spec(replicas):
        with open(spec_path, "w") as f:
            yaml.safe_dump({
                "name": "reload-e2e",
                "services": {"backend": {
                    "kind": "worker", "replicas": replicas,
                    "args": ["--model", "r-model", "--event-plane", "inproc"],
                }},
            }, f)

    write_spec(1)
    store = make_store("file", store_path)
    ctl = GraphController(
        store, GraphSpec.load(spec_path),
        runner=default_runner("file", store_path),
        interval_s=0.3, spec_path=spec_path, env={"JAX_PLATFORMS": "cpu"},
    ).start()
    try:
        await _wait(
            lambda: len([
                p for p in ctl._procs.get("backend", [])
                if p.popen.poll() is None
            ]) == 1,
            msg="initial spawn",
        )
        await asyncio.sleep(0.1)
        write_spec(2)  # CRD update analog
        await _wait(
            lambda: len([
                p for p in ctl._procs.get("backend", [])
                if p.popen.poll() is None
            ]) == 2,
            msg="hot reload to 2",
        )
    finally:
        await ctl.stop()
        await store.close()
