"""Engine checkpoint/restore (engine/checkpoint.py): codec round-trips and
crash-consistency classification.

Tier-1 on purpose: everything here is host-side file I/O over the G3 block
codec — no engine, no compile. The e2e elastic-reclaim path (drain →
checkpoint → kill → restore warm) runs in test_sim.py against the fleet
simulator.
"""

import json
import os

import numpy as np
import pytest

from dynamo_tpu.engine.checkpoint import (
    MANIFEST_NAME,
    CheckpointCorrupt,
    load_checkpoint,
    save_checkpoint,
)
from dynamo_tpu.kvbm.layout import BlockShape, QuantizedBlockCodec
from dynamo_tpu.runtime.faults import FAULTS, FaultInjected


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    FAULTS.disarm()


FLOAT_FMT = {"kind": "float", "dtype": "float32", "shape": [2, 2, 4, 3, 8]}


def _float_blocks(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (0x1000 + i, rng.standard_normal(FLOAT_FMT["shape"]).astype(np.float32))
        for i in range(n)
    ]


def test_float_round_trip_bit_exact(tmp_path):
    blocks = _float_blocks(5)
    manifest = save_checkpoint(
        str(tmp_path), blocks, block_format=dict(FLOAT_FMT),
        radix_order=[h for h, _ in blocks],
        queue=[{"request_id": "r1", "state": "running", "produced": 7}],
        weights_ref="sha256:abc",
    )
    assert manifest["blocks"] == [f"{h:016x}" for h, _ in blocks]

    state = load_checkpoint(str(tmp_path))
    assert state.blocks == [h for h, _ in blocks]
    assert state.radix == [h for h, _ in blocks]
    assert state.queue == [{"request_id": "r1", "state": "running", "produced": 7}]
    assert state.weights_ref == "sha256:abc"
    for h, arr in blocks:
        got = state.load_block(h)
        assert got.dtype == arr.dtype
        assert got.shape == arr.shape
        assert np.array_equal(got, arr)  # bit-exact, not allclose


def test_int8_codec_buffer_round_trip(tmp_path):
    shape = BlockShape(
        num_layers=2, block_size=4, num_kv_heads=3, head_dim=8,
        dtype=np.dtype(np.int8),
    )
    codec = QuantizedBlockCodec(shape)
    rng = np.random.default_rng(1)
    payload = rng.integers(-128, 128, size=codec.payload_shape, dtype=np.int8)
    scales = rng.standard_normal(codec.scales_shape).astype(np.float32)
    buf = codec.encode(payload, scales)

    save_checkpoint(
        str(tmp_path), [(0xFEED, buf)],
        block_format={"kind": "int8", "nbytes": codec.nbytes},
    )
    state = load_checkpoint(str(tmp_path))
    got_payload, got_scales = codec.decode(state.load_block(0xFEED))
    assert np.array_equal(got_payload, payload)
    # scale floats must survive bit-exactly too (pure byte moves)
    assert np.array_equal(
        got_scales.view(np.uint32), scales.view(np.uint32)
    )


def test_max_blocks_caps_checkpoint(tmp_path):
    manifest = save_checkpoint(
        str(tmp_path), _float_blocks(6), block_format=dict(FLOAT_FMT),
        max_blocks=2,
    )
    assert len(manifest["blocks"]) == 2
    assert len(load_checkpoint(str(tmp_path)).blocks) == 2


def test_missing_manifest_is_partial_checkpoint(tmp_path):
    # blocks staged, commit never happened: the crash-consistent partial-
    # checkpoint signature — restore must classify, not serve
    os.makedirs(tmp_path / "blocks")
    with pytest.raises(CheckpointCorrupt, match="partial"):
        load_checkpoint(str(tmp_path))


def test_truncated_manifest_rejected(tmp_path):
    save_checkpoint(str(tmp_path), _float_blocks(2), block_format=dict(FLOAT_FMT))
    mpath = tmp_path / MANIFEST_NAME
    raw = mpath.read_text()
    mpath.write_text(raw[: len(raw) // 2])
    with pytest.raises(CheckpointCorrupt, match="unreadable"):
        load_checkpoint(str(tmp_path))


def test_wrong_version_and_bad_structure_rejected(tmp_path):
    save_checkpoint(str(tmp_path), _float_blocks(1), block_format=dict(FLOAT_FMT))
    mpath = tmp_path / MANIFEST_NAME
    doc = json.loads(mpath.read_text())
    doc["version"] = 99
    mpath.write_text(json.dumps(doc))
    with pytest.raises(CheckpointCorrupt, match="version"):
        load_checkpoint(str(tmp_path))
    doc["version"] = 1
    doc["blocks"] = ["not-a-hash"]
    mpath.write_text(json.dumps(doc))
    with pytest.raises(CheckpointCorrupt, match="not a hash"):
        load_checkpoint(str(tmp_path))


def test_manifest_naming_missing_block_rejected(tmp_path):
    blocks = _float_blocks(3)
    save_checkpoint(str(tmp_path), blocks, block_format=dict(FLOAT_FMT))
    os.unlink(tmp_path / "blocks" / f"{blocks[1][0]:016x}.kv")
    with pytest.raises(CheckpointCorrupt, match="missing block"):
        load_checkpoint(str(tmp_path))


def test_torn_block_detected_on_load(tmp_path):
    blocks = _float_blocks(2)
    save_checkpoint(str(tmp_path), blocks, block_format=dict(FLOAT_FMT))
    h = blocks[0][0]
    bpath = tmp_path / "blocks" / f"{h:016x}.kv"
    bpath.write_bytes(bpath.read_bytes()[:-16])  # truncate the payload
    state = load_checkpoint(str(tmp_path))
    with pytest.raises(CheckpointCorrupt):
        state.load_block(h)
    # the untouched sibling still validates — content-addressed pages are
    # independently trustworthy (restore keeps the warm prefix)
    assert np.array_equal(state.load_block(blocks[1][0]), blocks[1][1])


def test_format_mismatch_rejected_per_block(tmp_path):
    blocks = _float_blocks(1)
    fmt = dict(FLOAT_FMT)
    fmt["shape"] = [2, 2, 4, 3, 4]  # manifest lies about head_dim
    save_checkpoint(str(tmp_path), blocks, block_format=fmt)
    state = load_checkpoint(str(tmp_path))
    with pytest.raises(CheckpointCorrupt, match="block format"):
        state.load_block(blocks[0][0])


def test_manifest_fault_dies_before_commit(tmp_path):
    # checkpoint.manifest fires BEFORE the atomic rename: the fault models a
    # death mid-commit, so no manifest may appear and no tmp may linger
    FAULTS.arm("checkpoint.manifest:fail@1")
    with pytest.raises(FaultInjected):
        save_checkpoint(
            str(tmp_path), _float_blocks(2), block_format=dict(FLOAT_FMT)
        )
    assert not (tmp_path / MANIFEST_NAME).exists()
    assert not [p for p in os.listdir(tmp_path) if p.startswith(MANIFEST_NAME)]
    with pytest.raises(CheckpointCorrupt, match="partial"):
        load_checkpoint(str(tmp_path))
    # the block files themselves are fine: a re-run checkpoint over the same
    # directory commits cleanly
    manifest = save_checkpoint(
        str(tmp_path), _float_blocks(2), block_format=dict(FLOAT_FMT)
    )
    assert len(load_checkpoint(str(tmp_path)).blocks) == len(manifest["blocks"])
