"""Planned reclaims (docs/operations.md §13): drain routing, the drain lease,
the /drain control endpoint, and the planner's announced-reclaim pre-warm.

The frontend-side regression here is the one the elastic-reclaim sim pins
e2e: a worker advertising ``state=draining`` must never be chosen — not for
new work and, critically, not as a MIGRATION destination (a retry landing on
a worker seconds from death just migrates twice).
"""

from types import SimpleNamespace

import aiohttp

from dynamo_tpu.engine.drain import DrainCoordinator, DrainLedger
from dynamo_tpu.llm.discovery import ModelPipeline
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.common import BackendOutput, PreprocessedRequest
from dynamo_tpu.planner.core import LoadSnapshot, PlannerConfig, PoolPlanner
from dynamo_tpu.planner.metrics_source import EventPlaneMetricsSource
from dynamo_tpu.runtime import HealthState, StatusServer
from dynamo_tpu.runtime.engine import Context


class _Stream:
    def __init__(self, wid, outs):
        self.instance_id = wid
        self._iter = iter(outs)

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return next(self._iter)
        except StopIteration:
            raise StopAsyncIteration


class _StubClient:
    """Discovery + transport stub: ``metadata`` drives the draining state,
    ``outs_for`` drives what each worker's stream yields."""

    def __init__(self, workers):
        self.instances = {
            wid: SimpleNamespace(metadata=dict(meta)) for wid, meta in workers.items()
        }
        self.outs_for = {}
        self.calls = []

    def instance_ids(self):
        return sorted(self.instances)

    async def generate(self, obj, context, instance_id):
        # instance_id None = client-routed (no frontend shun set): pick the
        # first worker, like the round-robin transport would
        wid = instance_id if instance_id is not None else self.instance_ids()[0]
        self.calls.append(wid)
        self.sent_prior = list(obj.get("prior_token_ids", []))
        return _Stream(wid, self.outs_for.get(wid, []))


def _pipeline(client, migration_limit=2):
    card = ModelDeploymentCard(name="m", migration_limit=migration_limit)
    p = ModelPipeline(None, card)
    p.client = client
    return p


async def test_draining_worker_never_migration_destination():
    # A dies mid-stream (error finish), B is draining, C is healthy: the
    # migration retry must route to C even though B looks alive in discovery
    a, b, c = 10, 11, 12
    client = _StubClient({
        a: {}, b: {"state": "draining"}, c: {},
    })
    client.outs_for[a] = [
        BackendOutput(token_ids=[1]),
        BackendOutput(finish_reason="error"),
    ]
    client.outs_for[c] = [BackendOutput(token_ids=[2, 3], finish_reason="stop")]
    p = _pipeline(client)

    req = PreprocessedRequest(request_id="r1", model="m", token_ids=[5, 6, 7])
    got = []
    async for out in p.migration.generate(req, Context("r1")):
        got.extend(out.token_ids)

    assert client.calls == [a, c], client.calls
    assert b not in client.calls  # the regression: no retry onto draining
    assert got == [1, 2, 3]
    assert client.sent_prior == [1]  # the replay carried A's progress to C


async def test_new_work_steers_around_draining():
    a, b = 20, 21
    client = _StubClient({a: {"state": "draining"}, b: {}})
    client.outs_for[b] = [BackendOutput(token_ids=[9], finish_reason="stop")]
    p = _pipeline(client)
    for i in range(4):
        req = PreprocessedRequest(request_id=f"n{i}", model="m", token_ids=[1])
        async for _ in p.migration.generate(req, Context(f"n{i}")):
            pass
    assert set(client.calls) == {b}


async def test_whole_pool_draining_falls_back_to_serving():
    # avoiding every draining worker would leave no candidate: a draining
    # worker (still serving until its deadline) beats NoResponders
    a, b = 30, 31
    client = _StubClient({a: {"state": "draining"}, b: {"state": "draining"}})
    for wid in (a, b):
        client.outs_for[wid] = [BackendOutput(token_ids=[1], finish_reason="stop")]
    p = _pipeline(client)
    req = PreprocessedRequest(request_id="f1", model="m", token_ids=[1])
    async for out in p.migration.generate(req, Context("f1")):
        assert out.finish_reason == "stop"
    assert len(client.calls) == 1 and client.calls[0] in (a, b)


def test_drain_ledger_single_lease():
    led = DrainLedger()
    tok = led.acquire_drain(30.0)
    assert tok is not None and led.draining
    assert led.acquire_drain(30.0) is None  # one drain per process
    led.release_drain(tok)
    assert not led.draining
    assert led.acquire_drain(5.0) is not None  # released lease re-acquirable


class _IdleEngine:
    def snapshot(self):
        return {"running": 0, "waiting": 0}


class _Served:
    def __init__(self):
        self.meta = {}

    async def update_metadata(self, m):
        self.meta.update(m)


async def test_drain_coordinator_flips_discovery_and_reports():
    served = _Served()
    fired = []
    coord = DrainCoordinator(
        _IdleEngine(), served, ckpt_dir=None, on_drained=lambda: fired.append(1)
    )
    # deadline comfortably above the default 2s evacuation margin, so the
    # quiesce wait gets a real budget
    summary = await coord.begin(deadline_s=5.0)
    assert served.meta["state"] == "draining"
    assert summary["state"] == "draining"
    assert summary["quiesced"] is True  # idle engine quiesces immediately
    assert summary["deadline_margin_s"] > 0
    assert fired == [1]
    assert not coord.ledger.draining  # lease released on the way out


async def test_drain_endpoint():
    served = _Served()
    coord = DrainCoordinator(_IdleEngine(), served, ckpt_dir=None)
    server = StatusServer(HealthState(), drain_fn=coord.begin)
    bare = StatusServer(HealthState())  # no drain handler wired
    await server.start()
    await bare.start()
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{bare.port}/drain", json={"deadline_s": 1}
            )
            assert r.status == 409  # this component cannot drain

            r = await s.post(
                f"http://127.0.0.1:{server.port}/drain",
                json={"deadline_s": "not-a-number"},
            )
            assert r.status == 400

            r = await s.post(
                f"http://127.0.0.1:{server.port}/drain", json={"deadline_s": 1.0}
            )
            assert r.status == 200
            body = await r.json()
            assert body["state"] == "draining"
            assert served.meta["state"] == "draining"
    finally:
        await server.stop()
        await bare.stop()


def test_planner_prewarms_announced_reclaims():
    cfg = PlannerConfig(min_replicas=1, max_replicas=16)
    pool = PoolPlanner("decode", "backend", None, cfg, lambda s: 100.0)
    pool.observe(100.0)  # steady state: exactly 1 replica of capacity
    base = pool.desired_replicas(LoadSnapshot())
    # two announced reclaims = two replicas of capacity already spoken for:
    # their replacements are requested BEFORE the deadline, not after the
    # post-kill latency spike
    bumped = pool.desired_replicas(LoadSnapshot(announced_reclaims=2))
    assert bumped == base + 2


def test_metrics_source_reclaim_window():
    now = [100.0]
    src = EventPlaneMetricsSource(None, "ns", [], clock=lambda: now[0])
    src.note_reclaim(7, deadline_ts=130.0)
    src.note_reclaim(8, deadline_ts=110.0)
    assert src.snapshot().announced_reclaims == 2
    now[0] = 115.0  # worker 8's deadline passed: it is dead, not announced
    assert src.snapshot().announced_reclaims == 1
    src.note_reclaim(7, deadline_ts=117.0)  # a later notice moves the deadline
    now[0] = 118.0
    assert src.snapshot().announced_reclaims == 0
    src.clear_reclaim(7)  # idempotent on an already-expired entry
    src.note_reclaim(9, deadline_ts=200.0)
    src.clear_reclaim(9)  # cancelled notice
    assert src.snapshot().announced_reclaims == 0
