"""Out-of-process weight service (engine/weight_service.py).

gpu_memory_service analog (reference lib/gpu_memory_service/README.md):
weights live in an owner process' tmpfs manifest; workers import zero-copy
over a unix socket, crashes return leases, restore beats disk reload.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dynamo_tpu.engine.weight_service import (
    WeightOwner,
    WeightServiceClient,
    load_params_served,
)

from test_hub_checkpoint import build_checkpoint


def _flat_equal(a, b):
    from dynamo_tpu.engine.warm import _flatten

    fa, fb = _flatten(a), _flatten(b)
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(
            np.asarray(fa[k], dtype=np.float32), np.asarray(fb[k], dtype=np.float32)
        )


async def test_import_matches_direct_load_and_survives_source_deletion(tmp_path):
    """First import parses the checkpoint; afterwards the disk copy is not
    needed at all — deleting it and importing again must still succeed
    (weights are owner-resident, the gms crash-survival property)."""
    ckpt = str(tmp_path / "ckpt")
    build_checkpoint(ckpt)
    from dynamo_tpu.engine.weights import config_from_hf, load_params

    cfg = config_from_hf(ckpt)
    direct = load_params(ckpt, cfg)

    sock = str(tmp_path / "wo.sock")
    owner = await WeightOwner(sock, root=str(tmp_path / "shm")).start()
    try:
        c1 = await asyncio.to_thread(WeightServiceClient, sock)
        params, info = await asyncio.to_thread(c1.import_params, ckpt, cfg)
        _flat_equal(params, direct)
        assert info["refs"] == 1

        # wipe the disk checkpoint: imports must keep working
        import shutil

        shutil.rmtree(ckpt)
        c2 = await asyncio.to_thread(WeightServiceClient, sock)
        params2, info2 = await asyncio.to_thread(c2.import_params, ckpt, cfg)
        _flat_equal(params2, direct)
        assert info2["refs"] == 2

        # live references refuse eviction; released ones don't
        with pytest.raises(RuntimeError, match="live references"):
            await asyncio.to_thread(c1.evict, ckpt)
        await asyncio.to_thread(c1.release, ckpt)
        await asyncio.to_thread(c2.release, ckpt)
        await asyncio.to_thread(c2.evict, ckpt)
        assert await asyncio.to_thread(c1.stat) == []
        c1.close()
        c2.close()
    finally:
        await owner.stop()


async def test_sigkill_worker_returns_lease(tmp_path):
    """A worker killed with SIGKILL never sends release; its socket EOF must
    reclaim every reference it held (connection-is-the-lease)."""
    ckpt = str(tmp_path / "ckpt")
    build_checkpoint(ckpt)
    sock = str(tmp_path / "wo.sock")
    owner = await WeightOwner(sock, root=str(tmp_path / "shm")).start()
    try:
        # a real OS process imports and then parks
        code = f"""
import sys, time
sys.path.insert(0, {json.dumps(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!s})
from dynamo_tpu.engine.weight_service import WeightServiceClient
c = WeightServiceClient({json.dumps(sock)})
params, info = c.import_params({json.dumps(ckpt)})
print("IMPORTED", info["refs"], flush=True)
time.sleep(600)
"""
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = await asyncio.wait_for(
            asyncio.to_thread(proc.stdout.readline), timeout=120
        )
        assert b"IMPORTED" in line, proc.stderr.read().decode()

        admin = await asyncio.to_thread(WeightServiceClient, sock)
        sets = await asyncio.to_thread(admin.stat)
        assert sets[0]["refs"] == 1

        proc.kill()
        proc.wait()
        for _ in range(100):
            sets = await asyncio.to_thread(admin.stat)
            if sets[0]["refs"] == 0:
                break
            await asyncio.sleep(0.05)
        assert sets[0]["refs"] == 0
        admin.close()
    finally:
        await owner.stop()


def _build_big_checkpoint(path: str, hidden=512, layers=6, inter=1536,
                          vocab=4096, heads=8, kvh=4, head_dim=64):
    """A checkpoint big enough (~tens of MB) that disk parse time dominates
    socket round-trip noise — the tiny hub-test checkpoint loads in ~2ms
    either way."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({
            "model_type": "llama", "vocab_size": vocab, "hidden_size": hidden,
            "num_hidden_layers": layers, "num_attention_heads": heads,
            "num_key_value_heads": kvh, "head_dim": head_dim,
            "intermediate_size": inter, "rope_theta": 10000.0,
            "rms_norm_eps": 1e-6, "max_position_embeddings": 512,
            "tie_word_embeddings": False,
        }, f)
    rng = np.random.default_rng(7)

    def w(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    q = heads * head_dim
    kv = kvh * head_dim
    tensors = {
        "model.embed_tokens.weight": w(vocab, hidden),
        "model.norm.weight": w(hidden),
        "lm_head.weight": w(vocab, hidden),
    }
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors.update({
            p + "input_layernorm.weight": w(hidden),
            p + "post_attention_layernorm.weight": w(hidden),
            p + "self_attn.q_proj.weight": w(q, hidden),
            p + "self_attn.k_proj.weight": w(kv, hidden),
            p + "self_attn.v_proj.weight": w(kv, hidden),
            p + "self_attn.o_proj.weight": w(hidden, q),
            p + "mlp.gate_proj.weight": w(inter, hidden),
            p + "mlp.up_proj.weight": w(inter, hidden),
            p + "mlp.down_proj.weight": w(hidden, inter),
        })
    save_file(tensors, os.path.join(path, "model.safetensors"))


async def test_shm_restore_beats_disk_reload(tmp_path):
    """The VERDICT contract: respawned worker's weight restore via the
    service must beat re-parsing the checkpoint from disk. The import is a
    manifest read + mmap (no byte copies); the disk path re-parses
    safetensors and re-casts dtypes."""
    ckpt = str(tmp_path / "ckpt")
    _build_big_checkpoint(ckpt)
    from dynamo_tpu.engine.weights import config_from_hf, load_params

    cfg = config_from_hf(ckpt)

    sock = str(tmp_path / "wo.sock")
    owner = await WeightOwner(sock, root=str(tmp_path / "shm")).start()
    try:
        # owner pays the parse once
        c = await asyncio.to_thread(WeightServiceClient, sock)
        await asyncio.to_thread(c.import_params, ckpt, cfg)

        t0 = time.perf_counter()
        disk = load_params(ckpt, cfg)
        t_disk = time.perf_counter() - t0

        def respawn_import():
            cc = WeightServiceClient(sock)
            t1 = time.perf_counter()
            params, _ = cc.import_params(ckpt, cfg)
            dt = time.perf_counter() - t1
            cc.close()
            return params, dt

        params, t_shm = await asyncio.to_thread(respawn_import)
        _flat_equal(params, disk)
        # generous margin: mmap import is ~2 orders faster; assert 1x
        assert t_disk > 0.02, f"checkpoint too small to measure ({t_disk})"
        assert t_shm < t_disk, (t_shm, t_disk)
        c.close()
    finally:
        await owner.stop()


async def test_load_params_served_falls_back_without_owner(tmp_path, monkeypatch):
    ckpt = str(tmp_path / "ckpt")
    build_checkpoint(ckpt)
    from dynamo_tpu.engine.weights import config_from_hf

    cfg = config_from_hf(ckpt)
    monkeypatch.setenv("DTPU_WARM_CACHE", str(tmp_path / "warm"))
    params, lease = load_params_served(
        ckpt, cfg, sock_path=str(tmp_path / "missing.sock")
    )
    assert lease is None
    assert "layers" in params


async def test_cli_owner_process_serves_imports(tmp_path):
    """The ``python -m dynamo_tpu.engine.weight_service`` entry: spawn a
    real owner process, import against it, shut it down over the wire."""
    ckpt = str(tmp_path / "ckpt")
    build_checkpoint(ckpt)
    sock = str(tmp_path / "wo.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.engine.weight_service",
         "--sock", sock, "--root", str(tmp_path / "shm")],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        # cold jax import in the owner process can take 30s+ under load
        for _ in range(180):
            if os.path.exists(sock) or proc.poll() is not None:
                break
            await asyncio.sleep(0.5)
        assert os.path.exists(sock), proc.stderr.read().decode()
        c = await asyncio.to_thread(WeightServiceClient, sock)
        params, info = await asyncio.to_thread(c.import_params, ckpt)
        assert info["bytes"] > 0
        assert "layers" in params
        c.shutdown_owner()
        c.close()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
