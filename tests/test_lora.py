"""Multi-LoRA serving (dynamo_tpu/lora/): adapter tables, engine-level
per-slot isolation, cache/convert, HRW routing.

Reference analogs: lib/llm/src/lora/{cache,source}.rs, routing/{hrw,table}.rs,
load/unload/list endpoints (components/src/dynamo/vllm/main.py:712).
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.kv_router.protocols import WorkerWithDpRank
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.lora import (
    LoRACache,
    LoraAdapterTable,
    LoraReplicaConfig,
    RendezvousHasher,
    allocate,
    load_adapter,
    make_lora_fn,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.runtime.engine import Context


def _cfg():
    return LlamaConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=96, dtype=jnp.float32,
    )


def _adapter_weights(cfg, rank=4, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    L, H = cfg.num_layers, cfg.hidden_size
    w = {}
    for t, out in (("wq", cfg.q_size), ("wk", cfg.kv_size),
                   ("wv", cfg.kv_size), ("wo", cfg.hidden_size)):
        inp = cfg.q_size if t == "wo" else H
        w[f"{t}.A"] = rng.standard_normal((L, inp, rank)).astype(np.float32) * scale
        w[f"{t}.B"] = rng.standard_normal((L, rank, out)).astype(np.float32) * scale
    return w


# ------------------------------------------------------------- table math
def test_adapter_table_load_unload_and_delta():
    cfg = _cfg()
    table = LoraAdapterTable(cfg, max_adapters=2, rank=4, dtype=jnp.float32)
    assert table.slot_of(None) == 0
    assert table.slot_of("missing") == 0

    w = _adapter_weights(cfg, rank=4, seed=1)
    slot = table.load("adapter-a", w, alpha=8.0)
    assert slot == 1
    assert table.list_adapters() == ["adapter-a"]
    assert table.slot_of("adapter-a") == 1

    # delta math: for slot 1, lora(name, li, x) == scale * x @ A @ B
    lora = make_lora_fn(table.tables(), jnp.int32(1))
    x = jnp.asarray(np.random.default_rng(2).standard_normal((3, cfg.hidden_size)), jnp.float32)
    got = lora("wq", 0, x)
    want = (8.0 / 4.0) * np.asarray(x) @ w["wq.A"][0] @ w["wq.B"][0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4)

    # slot 0 (identity) must contribute exactly zero
    lora0 = make_lora_fn(table.tables(), jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(lora0("wq", 0, x)), 0.0)

    assert table.unload("adapter-a")
    assert table.list_adapters() == []
    # tables are rebound functionally; a FRESH fn (as the engine builds per
    # dispatch via _lora_tables()) sees the cleared slot
    lora_fresh = make_lora_fn(table.tables(), jnp.int32(1))
    np.testing.assert_array_equal(np.asarray(lora_fresh("wq", 0, x)), 0.0)


def test_adapter_table_rank_padding_and_slots_exhaust():
    cfg = _cfg()
    table = LoraAdapterTable(cfg, max_adapters=1, rank=8, dtype=jnp.float32)
    table.load("small-rank", _adapter_weights(cfg, rank=4))  # pads 4 -> 8
    with pytest.raises(RuntimeError):
        table.load("overflow", _adapter_weights(cfg, rank=4))
    with pytest.raises(ValueError):
        LoraAdapterTable(cfg, max_adapters=1, rank=2).load(
            "too-big", _adapter_weights(cfg, rank=4)
        )


# ------------------------------------------------------------- engine e2e
def _req(rid, lora=None, n=4):
    ann = {"lora": lora} if lora else {}
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=list(range(10)),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
        annotations=ann,
    )


@pytest.mark.slow
def test_engine_lora_changes_output_per_slot():
    """Same prompt, three concurrent requests: base, adapter-a, adapter-b.
    The base stream must be identical to a no-LoRA engine's output (slot-0
    identity), and each adapter must change the stream its own way."""
    cfg = TpuEngineConfig(
        model=_cfg(), num_blocks=128, block_size=16, max_batch_size=4,
        max_context=128, prefill_buckets=(16, 32, 64),
        lora_max_adapters=2, lora_rank=4,
    )

    async def run(engine, loras):
        outs = await asyncio.gather(*[
            _collect(engine, _req(f"r{i}", lora=l)) for i, l in enumerate(loras)
        ])
        engine.stop()
        return outs

    async def _collect(engine, req):
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
        return toks

    engine = TpuEngine(cfg)
    mcfg = cfg.model
    engine.lora.load("adapter-a", _adapter_weights(mcfg, rank=4, seed=5, scale=2.0))
    engine.lora.load("adapter-b", _adapter_weights(mcfg, rank=4, seed=9, scale=2.0))
    base, wa, wb = asyncio.run(run(engine, [None, "adapter-a", "adapter-b"]))

    plain_engine = TpuEngine(TpuEngineConfig(
        model=_cfg(), num_blocks=128, block_size=16, max_batch_size=4,
        max_context=128, prefill_buckets=(16, 32, 64),
    ))
    (plain,) = asyncio.run(run(plain_engine, [None]))

    assert base == plain, "slot-0 identity must not perturb the base model"
    assert wa != base and wb != base and wa != wb


def test_engine_rejects_unknown_adapter():
    cfg = TpuEngineConfig(
        model=_cfg(), num_blocks=64, block_size=16, max_batch_size=2,
        max_context=64, prefill_buckets=(16, 32),
        lora_max_adapters=1,
    )
    engine = TpuEngine(cfg)

    async def run():
        with pytest.raises(ValueError, match="unknown LoRA adapter"):
            async for _ in engine.generate(_req("r", lora="ghost"), Context()):
                pass
        engine.stop()

    asyncio.run(run())


# ------------------------------------------------------------- cache + npz
def test_cache_and_npz_roundtrip(tmp_path):
    cfg = _cfg()
    w = _adapter_weights(cfg, rank=4, seed=3)
    path = tmp_path / "adapter.npz"
    np.savez(path, alpha=np.float32(16.0), **w)
    weights, alpha = load_adapter(str(path))
    assert alpha == 16.0
    np.testing.assert_array_equal(weights["wq.A"], w["wq.A"])

    cache = LoRACache(root=str(tmp_path / "cache"))
    key1 = cache.uri_to_key("file:///a/b/adapter-x")
    assert key1 == cache.uri_to_key("file:///a/b/adapter-x")
    assert key1 != cache.uri_to_key("file:///other/adapter-x")


# ------------------------------------------------------------- routing
def test_hrw_routing_is_deterministic_and_minimal():
    workers = [WorkerWithDpRank(i, 0) for i in range(1, 6)]
    a = RendezvousHasher.replica_set("my-lora", workers, 2)
    b = RendezvousHasher.replica_set("my-lora", workers, 2)
    assert a == b and len(a) == 2
    # removing an unrelated worker must not move the adapter
    survivors = [w for w in workers if w not in a]
    reduced = [w for w in workers if w != survivors[0]]
    assert RendezvousHasher.replica_set("my-lora", reduced, 2) == a

    table = allocate(["l1", "l2", "l3"], workers, replicas=2)
    assert len(table) == 3
    assert table.list_loras() == ["l1", "l2", "l3"]
    assert len(table.get_replica_set("l1")) == 2
    table.update_allocation("l1", LoraReplicaConfig("l1", 1, workers[:1]))
    assert table.get_replica_set("l1") == workers[:1]
    assert table.remove_lora("l2") is not None
    assert table.get_replica_set("l2") is None
