"""gpt-oss family engine tests: sliding-window + sink attention through the
paged serving path.

The oracle test regenerates greedily with a full causal recompute per step
(no KV cache, no paging) and requires the engine's paged/windowed decode to
produce identical tokens — that equivalence is what makes the windowed
paged path trustworthy.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import gptoss
from dynamo_tpu.ops import attention as att
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.runtime.engine import Context


def _cfg(**kw):
    return gptoss.GptOssConfig.tiny_gptoss(**kw)


def engine_for(cfg, tp=1, **kw):
    defaults = dict(
        num_blocks=64, block_size=4, max_batch_size=4, max_context=256,
        prefill_buckets=(16, 32, 64, 128, 256), tp=tp,
    )
    defaults.update(kw)
    mesh = make_mesh(tp=tp, devices=jax.devices()[:tp])
    return TpuEngine(TpuEngineConfig(model=cfg, **defaults), mesh=mesh)


def greedy_req(rid, tokens, max_tokens=8):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def _run(engine, req):
    toks = []
    async for out in engine.generate(req, Context()):
        toks.extend(out.token_ids)
    return toks


def _oracle_greedy(params, cfg, prompt, n):
    """Greedy continuation by full causal recompute per step — no paging,
    no KV cache; the window/sink semantics come straight from
    ops.causal_attention."""
    toks = list(prompt)
    for _ in range(n):
        ids = jnp.asarray(toks, jnp.int32)
        pos = jnp.arange(len(toks), dtype=jnp.int32)
        hidden = gptoss.forward(
            params, cfg, ids, pos,
            lambda q, k, v, i, **kw: att.causal_attention(q, k, v, **kw),
        )
        logits = gptoss.lm_logits(params, cfg, hidden)
        toks.append(int(jnp.argmax(logits[-1])))
    return toks[len(prompt):]


def test_window_changes_attention():
    """The sliding window must actually alter outputs once the context
    exceeds it (otherwise the mask is dead code)."""
    cfg = _cfg()
    p = gptoss.init_params(jax.random.PRNGKey(0), cfg)
    ids = jnp.arange(20, dtype=jnp.int32)
    pos = jnp.arange(20, dtype=jnp.int32)
    windowed = gptoss.forward(
        p, cfg, ids, pos,
        lambda q, k, v, i, **kw: att.causal_attention(q, k, v, **kw),
    )
    full = gptoss.forward(
        p, cfg, ids, pos,
        lambda q, k, v, i, **kw: att.causal_attention(
            q, k, v, window=None, sinks=kw.get("sinks")
        ),
    )
    # positions inside the window agree; positions past it diverge
    assert np.allclose(np.asarray(windowed[:8]), np.asarray(full[:8]), atol=1e-5)
    assert not np.allclose(np.asarray(windowed[-1]), np.asarray(full[-1]), atol=1e-5)


@pytest.mark.slow
async def test_engine_matches_full_recompute_oracle():
    """Paged windowed decode == full causal recompute, token for token,
    with the context crossing the window boundary mid-generation."""
    cfg = _cfg()
    engine = engine_for(cfg)
    try:
        prompt = [int(x) for x in
                  jax.random.randint(jax.random.PRNGKey(7), (12,), 5, 500)]
        got = await _run(engine, greedy_req("a", prompt, max_tokens=8))
        want = _oracle_greedy(engine.params, cfg, prompt, 8)
        assert got == want
    finally:
        engine.stop()


@pytest.mark.slow
async def test_engine_gptoss_tp2_matches_tp1():
    cfg = _cfg()
    prompt = list(range(30, 50))
    e1 = engine_for(cfg)
    try:
        t1 = await _run(e1, greedy_req("a", prompt))
    finally:
        e1.stop()
    e2 = engine_for(cfg, tp=2)
    try:
        t2 = await _run(e2, greedy_req("b", prompt))
    finally:
        e2.stop()
    assert t1 == t2


@pytest.mark.slow
async def test_engine_gptoss_chunked_prefill():
    """A prompt longer than every prefill bucket runs as chunks; the
    windowed extend path must reproduce the single-chunk result."""
    cfg = _cfg()
    prompt = [int(x) for x in
              jax.random.randint(jax.random.PRNGKey(3), (50,), 5, 500)]
    e1 = engine_for(cfg, prefill_buckets=(64, 128))
    try:
        t1 = await _run(e1, greedy_req("a", prompt, max_tokens=4))
    finally:
        e1.stop()
    e2 = engine_for(cfg, prefill_buckets=(16, 32), max_context=256)
    try:
        t2 = await _run(e2, greedy_req("b", prompt, max_tokens=4))
    finally:
        e2.stop()
    assert t1 == t2


def test_unsupported_paths_fail_fast():
    import pytest

    cfg = _cfg()
    mesh = make_mesh(tp=1, sp=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="ring"):
        TpuEngine(
            TpuEngineConfig(model=cfg, num_blocks=32, block_size=4,
                            max_batch_size=2, max_context=64,
                            prefill_buckets=(16, 32), sp=2),
            mesh=mesh,
        )
    # use_pallas is no longer rejected: the unified kernel's per-row
    # window/sink attributes serve these layers (windowed decode routes
    # through unified q_len=1 rows; e2e parity in test_mixed_batching)
    e = TpuEngine(
        TpuEngineConfig(model=cfg, num_blocks=32, block_size=4,
                        max_batch_size=2, max_context=64,
                        prefill_buckets=(16, 32), use_pallas=True),
        mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
    )
    assert e.use_pallas  # (mixed needs DTPU_MIXED, pinned off suite-wide)
    e.stop()


@pytest.mark.slow
async def test_engine_gptoss_prefix_reuse_matches():
    """Second request sharing a long prefix reuses cached blocks; the
    windowed extend attention over the cached prefix must produce the same
    greedy continuation as the cold path."""
    cfg = _cfg()
    engine = engine_for(cfg)
    try:
        prefix = [int(x) for x in
                  jax.random.randint(jax.random.PRNGKey(11), (24,), 5, 500)]
        cold = await _run(engine, greedy_req("cold", prefix))
        cached = None
        req = greedy_req("warm", prefix)
        toks = []
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.annotations:
                cached = out.annotations.get("cached_tokens", cached)
        assert toks == cold
        assert cached and cached > 0
    finally:
        engine.stop()
