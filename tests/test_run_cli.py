"""dynamo-run CLI analog (dynamo_tpu/run.py): in=<input> out=<engine>.

Reference analog: launch/dynamo-run (main.rs:30-33, opt.rs:6-17).
"""

import pytest

import json
import subprocess
import sys


def _run(args, input_text=None, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run", *args],
        capture_output=True, text=True, timeout=timeout, input=input_text,
        cwd="/root/repo",
    )


def test_text_in_echo_out():
    """Echo engine + byte tokenizer: the output reproduces the prompt."""
    r = _run(["in=text:hello", "out=echo", "--platform", "cpu"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "hello" in r.stdout


def test_batch_in_mocker_out(tmp_path):
    f = tmp_path / "prompts.txt"
    f.write_text("first prompt\nsecond prompt\n")
    r = _run([f"in=batch:{f}", "out=mocker", "--max-tokens", "4",
              "--speedup", "100", "--platform", "cpu"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.splitlines() if l.startswith("{")]
    assert [l["index"] for l in lines] == [0, 1]
    assert lines[0]["prompt"] == "first prompt"
    assert all(l["text"] for l in lines)


def test_bad_input_errors():
    r = _run(["in=telepathy", "out=echo", "--platform", "cpu"])
    assert r.returncode != 0


@pytest.mark.slow
def test_text_in_mla_preset_out():
    """One-shot generation through a real MLA (DeepSeek-style) engine
    preset — the latent-KV serving path reachable from the CLI."""
    r = _run(["in=text:hi", "out=tiny-mla", "--max-tokens", "3",
              "--platform", "cpu"], timeout=300)
    assert r.returncode == 0, r.stderr[-800:]
    assert r.stdout.strip()


@pytest.mark.slow
def test_text_in_gptoss_preset_out():
    """One-shot generation through the gpt-oss preset (sinks + sliding
    window attention) from the CLI."""
    r = _run(["in=text:hi", "out=tiny-gptoss", "--max-tokens", "3",
              "--platform", "cpu"], timeout=300)
    assert r.returncode == 0, r.stderr[-800:]
    assert r.stdout.strip()
