"""Layered config resolution (runtime/config.py): defaults < file < env <
kwargs — the figment analog (reference lib/runtime/src/config.rs)."""

import json


from dynamo_tpu.runtime.config import (
    ENV_CONFIG_FILE,
    ENV_STORE,
    RuntimeConfig,
    is_truthy,
    load_config_file,
)


def test_defaults():
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "mem"
    assert cfg.request_plane == "tcp"


def test_file_then_env_then_kwargs(tmp_path, monkeypatch):
    f = tmp_path / "dtpu.json"
    f.write_text(json.dumps({
        "store": "file", "store_path": "/from/file", "lease_ttl_s": 3.5,
    }))
    monkeypatch.setenv(ENV_CONFIG_FILE, str(f))
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "file"
    assert cfg.store_path == "/from/file"
    assert cfg.lease_ttl_s == 3.5

    # env outranks the file
    monkeypatch.setenv(ENV_STORE, "tcp")
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "tcp"
    assert cfg.store_path == "/from/file"

    # explicit kwargs outrank everything
    cfg = RuntimeConfig.from_env(store="mem")
    assert cfg.store == "mem"


def test_toml_config(tmp_path, monkeypatch):
    f = tmp_path / "dtpu.toml"
    f.write_text('store = "file"\nlease_ttl_s = 7.0\n')
    monkeypatch.setenv(ENV_CONFIG_FILE, str(f))
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "file" and cfg.lease_ttl_s == 7.0
    assert load_config_file(str(f))["store"] == "file"


def test_bad_env_value_falls_back(monkeypatch):
    monkeypatch.setenv("DTPU_SYSTEM_PORT", "not-a-number")
    assert RuntimeConfig.from_env().system_port == 0


def test_truthy():
    assert is_truthy("1") and is_truthy("True") and is_truthy("on")
    assert not is_truthy("0") and not is_truthy(None) and not is_truthy("nope")
