"""Layered config resolution (runtime/config.py): defaults < file < env <
kwargs — the figment analog (reference lib/runtime/src/config.rs)."""

import json


from dynamo_tpu.runtime.config import (
    ENV_CONFIG_FILE,
    ENV_STORE,
    RuntimeConfig,
    is_truthy,
    load_config_file,
)


def test_defaults():
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "mem"
    assert cfg.request_plane == "tcp"


def test_file_then_env_then_kwargs(tmp_path, monkeypatch):
    f = tmp_path / "dtpu.json"
    f.write_text(json.dumps({
        "store": "file", "store_path": "/from/file", "lease_ttl_s": 3.5,
    }))
    monkeypatch.setenv(ENV_CONFIG_FILE, str(f))
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "file"
    assert cfg.store_path == "/from/file"
    assert cfg.lease_ttl_s == 3.5

    # env outranks the file
    monkeypatch.setenv(ENV_STORE, "tcp")
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "tcp"
    assert cfg.store_path == "/from/file"

    # explicit kwargs outrank everything
    cfg = RuntimeConfig.from_env(store="mem")
    assert cfg.store == "mem"


def test_toml_config(tmp_path, monkeypatch):
    f = tmp_path / "dtpu.toml"
    f.write_text('store = "file"\nlease_ttl_s = 7.0\n')
    monkeypatch.setenv(ENV_CONFIG_FILE, str(f))
    cfg = RuntimeConfig.from_env()
    assert cfg.store == "file" and cfg.lease_ttl_s == 7.0
    assert load_config_file(str(f))["store"] == "file"


def test_bad_env_value_falls_back(monkeypatch):
    monkeypatch.setenv("DTPU_SYSTEM_PORT", "not-a-number")
    assert RuntimeConfig.from_env().system_port == 0


def test_truthy():
    assert is_truthy("1") and is_truthy("True") and is_truthy("on")
    assert not is_truthy("0") and not is_truthy(None) and not is_truthy("nope")


def test_env_catalog_knobs_reach_their_defaults(monkeypatch):
    """The ENV-DRIFT cleanup wired the previously-dead catalog entries to
    their natural defaults: env configures what callers leave open, and an
    explicit value always wins."""
    # DTPU_MIGRATION_LIMIT applies at the worker CLI boundary only: an
    # explicit migration_limit=0 (migration disabled) must stay 0 even
    # with the fleet env set
    monkeypatch.setenv("DTPU_MIGRATION_LIMIT", "4")
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.runtime.config import ENV_MIGRATION_LIMIT, env_int

    async def _send(req, ctx, excluded):  # pragma: no cover — never called
        raise AssertionError

    assert Migration(_send, migration_limit=0).migration_limit == 0
    assert Migration(_send, migration_limit=2).migration_limit == 2
    assert env_int(ENV_MIGRATION_LIMIT, 0) == 4  # the CLI default's source

    monkeypatch.setenv("DTPU_CANARY_WAIT_TIME", "0.25")
    from dynamo_tpu.runtime.health import EndpointCanary, StatusServer

    assert EndpointCanary({}).interval_s == 0.25
    assert EndpointCanary({}, interval_s=3.0).interval_s == 3.0

    monkeypatch.setenv("DTPU_SYSTEM_HOST", "127.0.0.9")
    from dynamo_tpu.runtime.health import HealthState

    assert StatusServer(HealthState()).host == "127.0.0.9"
    assert StatusServer(HealthState(), host="0.0.0.0").host == "0.0.0.0"

    monkeypatch.setenv("DTPU_ROUTER_REPLICA_SYNC", "1")
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig

    assert KvRouterConfig().replica_sync is True
    assert KvRouterConfig(replica_sync=False).replica_sync is False

    monkeypatch.setenv("DTPU_KV_BLOCK_SIZE", "32")
    from dynamo_tpu.engine.engine import TpuEngineConfig
    from dynamo_tpu.models.llama import LlamaConfig

    model = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=1)
    assert TpuEngineConfig(model).block_size == 32
    assert TpuEngineConfig(model, block_size=8).block_size == 8
