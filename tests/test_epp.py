"""Endpoint picker (deploy/epp.py) — the inference-gateway EPP analog.

The round-3 verdict's only hard "no" row: gateways need a picker that
scores backends with the framework's KV router. The test registers two
mocker workers, primes one with a prompt's KV events, and asserts the
picker sends that prompt to the primed worker (prefix affinity) while
fresh prompts spread by load.
"""

import asyncio

import aiohttp

from dynamo_tpu.deploy.epp import EndpointPicker
from dynamo_tpu.llm import ModelDeploymentCard, register_llm
from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
from dynamo_tpu.runtime import (
    DistributedRuntime,
    InProcEventPlane,
    MemKVStore,
    RuntimeConfig,
)
from dynamo_tpu.kv_router import KvEventPublisher


def make_rt(store, plane):
    cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
    return DistributedRuntime(cfg, store=store, event_plane=plane)


async def _worker(store, plane, name="epp-model"):
    rt = await make_rt(store, plane).start()
    card = ModelDeploymentCard(
        name=name, tokenizer="byte", context_length=4096, kv_block_size=16,
    )
    engine = MockerEngine(MockEngineArgs(block_size=16))
    served = await register_llm(rt, engine, card)
    pub = KvEventPublisher(
        plane, card.namespace, card.component,
        worker_id=served.instance_id, block_size=16,
    )
    return rt, served, pub


async def test_pick_prefers_kv_overlap(tmp_path):
    from dynamo_tpu.tokens import compute_sequence_hashes

    store = MemKVStore()
    plane = InProcEventPlane()
    rt1, served1, pub1 = await _worker(store, plane)
    rt2, served2, pub2 = await _worker(store, plane)
    picker_rt = await make_rt(store, plane).start()
    picker = EndpointPicker(picker_rt, host="127.0.0.1", port=0)
    await picker.start()
    try:
        pipe = None
        for _ in range(100):
            pipe = picker.manager.get("epp-model")
            if pipe and len(pipe.client.instances) == 2:
                break
            await asyncio.sleep(0.05)
        assert pipe is not None and len(pipe.client.instances) == 2

        # worker 1 announces it holds this prompt's first 4 blocks
        prompt = list(range(64))
        hashes = compute_sequence_hashes(prompt, 16)
        await pub1.stored(hashes)
        await asyncio.sleep(0.2)  # let the router index the events

        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{picker.port}/pick",
                json={"model": "epp-model", "token_ids": prompt},
            )
            assert r.status == 200, await r.text()
            body = await r.json()
        assert int(body["instance_id"], 16) == served1.instance_id
        assert body["overlap_blocks"] >= 1
        assert body["address"]

        # unknown model -> 404
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{picker.port}/pick",
                json={"model": "nope", "token_ids": [1]},
            )
            assert r.status == 404
    finally:
        await picker.stop()
        await picker_rt.shutdown()
        await served1.stop()
        await served2.stop()
        await rt1.shutdown()
        await rt2.shutdown()


def test_helm_chart_is_well_formed():
    """The chart's values/Chart parse, templates cover the graph, and the
    worker template wires tp/sp/pp chips into the TPU resource request."""
    import os

    import yaml

    root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy", "helm", "dynamo-tpu",
    )
    chart = yaml.safe_load(open(os.path.join(root, "Chart.yaml")))
    assert chart["name"] == "dynamo-tpu" and chart["apiVersion"] == "v2"
    values = yaml.safe_load(open(os.path.join(root, "values.yaml")))
    assert "workers" in values and "frontend" in values and "store" in values
    tmpl_dir = os.path.join(root, "templates")
    templates = {f: open(os.path.join(tmpl_dir, f)).read()
                 for f in os.listdir(tmpl_dir)}
    assert {"frontend.yaml", "workers.yaml", "netstore.yaml",
            "epp.yaml", "kvbm.yaml"} <= set(templates)
    w = templates["workers.yaml"]
    assert "google.com/tpu" in w and "dynamo_tpu.engine" in w
    assert '"--pp"' in w  # pipeline parallelism reaches the pod spec
    assert "DTPU_STORE" in templates["_helpers.tpl"]
