"""Unit tests for the deterministic fault-injection plane (runtime/faults.py):
spec parsing, call-indexed schedules, seeded reproducibility, and the
sync/async/corrupt injection surfaces.
"""

import time

import pytest

from dynamo_tpu.runtime.faults import (
    FaultInjected,
    FaultRegistry,
    FaultRule,
    InjectedDrop,
    parse_faults,
    reload_from_env,
)
from dynamo_tpu.runtime import faults as faults_mod


# -- parsing -----------------------------------------------------------------

def test_parse_issue_example():
    rules = parse_faults("transfer.pull:drop@2;etcd.watch:delay=0.5@seed=7")
    assert len(rules) == 2
    r0, r1 = rules
    assert (r0.point, r0.action, r0.nth, r0.from_nth) == ("transfer.pull", "drop", 2, False)
    assert (r1.point, r1.action, r1.value, r1.seed) == ("etcd.watch", "delay", 0.5, 7)
    assert r1.prob == 0.5  # bare seed implies a coin-flip schedule


def test_parse_qualifiers():
    (r,) = parse_faults("a.b:fail@3+")
    assert r.nth == 3 and r.from_nth
    (r,) = parse_faults("a.b:drop@p=0.25@seed=11")
    assert r.prob == 0.25 and r.seed == 11
    (r,) = parse_faults("a.b:hang=2.5")
    assert r.action == "hang" and r.value == 2.5


@pytest.mark.parametrize("bad", [
    "no-colon", "p:unknownaction", "a.b:delay",  # delay without value
    "a.b:fail@wat", "a.b:drop@p=x",
])
def test_parse_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        parse_faults(bad)


# -- schedules ---------------------------------------------------------------

def test_nth_call_schedule():
    reg = FaultRegistry()
    reg.arm("p:fail@3")
    reg.inject("p")
    reg.inject("p")
    with pytest.raises(FaultInjected):
        reg.inject("p")
    reg.inject("p")  # only the 3rd call fires
    assert reg.fired == [("p", "fail", 3)]


def test_from_nth_schedule():
    reg = FaultRegistry()
    reg.arm("p:drop@2+")
    reg.inject("p")
    for _ in range(3):
        with pytest.raises(InjectedDrop):
            reg.inject("p")
    assert [i for _, _, i in reg.fired] == [2, 3, 4]


def test_seeded_schedule_is_reproducible():
    def run(seed):
        reg = FaultRegistry()
        reg.arm(f"p:drop@p=0.5@seed={seed}")
        outcomes = []
        for _ in range(40):
            try:
                reg.inject("p")
                outcomes.append(False)
            except InjectedDrop:
                outcomes.append(True)
        return outcomes, reg.fired

    a_out, a_fired = run(7)
    b_out, b_fired = run(7)
    c_out, _ = run(8)
    assert a_out == b_out and a_fired == b_fired  # same seed => same schedule
    assert a_out != c_out                          # different seed differs
    assert any(a_out) and not all(a_out)           # an actual mix


def test_plan_matches_live_fired_log():
    reg = FaultRegistry()
    reg.arm("p:drop@p=0.3@seed=5")
    plan = reg.plan("p", 25)
    for _ in range(25):
        try:
            reg.inject("p")
        except InjectedDrop:
            pass
    assert [(i, a) for _, a, i in reg.fired] == plan


def test_delay_action_sleeps():
    reg = FaultRegistry()
    reg.arm("p:delay=0.05")
    t0 = time.monotonic()
    reg.inject("p")
    assert time.monotonic() - t0 >= 0.04


async def test_async_inject_and_delay():
    reg = FaultRegistry()
    reg.arm("p:delay=0.02;p:drop@2")
    t0 = time.monotonic()
    await reg.ainject("p")
    assert time.monotonic() - t0 >= 0.015
    with pytest.raises(InjectedDrop):
        await reg.ainject("p")


def test_corrupt_uses_its_own_counter():
    reg = FaultRegistry()
    reg.arm("p:corrupt@2;p:drop@1")
    with pytest.raises(InjectedDrop):
        reg.inject("p")           # drop rule: inject counter call 1
    assert reg.mangle("p", b"abc") == b"abc"      # corrupt call 1: no fire
    assert reg.mangle("p", b"abc") != b"abc"      # corrupt call 2: flipped
    assert reg.mangle("p", b"") == b""            # empty payload unharmed


def test_disarm_clears_everything():
    reg = FaultRegistry()
    reg.arm("p:fail")
    assert reg.armed
    reg.disarm()
    assert not reg.armed
    reg.inject("p")  # no-op
    assert reg.fired == []


def test_unarmed_fast_path_costs_nothing():
    reg = FaultRegistry()
    reg.inject("anything")
    assert reg.calls("anything") == 0  # counters untouched when unarmed


def test_typed_error_codes():
    assert FaultInjected.code == "fault_injected"
    assert issubclass(InjectedDrop, ConnectionError)  # migration-retryable


def test_reload_from_env(monkeypatch):
    monkeypatch.setenv("DTPU_FAULTS", "env.point:fail@1")
    reload_from_env()
    try:
        with pytest.raises(FaultInjected):
            faults_mod.FAULTS.inject("env.point")
    finally:
        monkeypatch.delenv("DTPU_FAULTS")
        reload_from_env()
    assert not faults_mod.FAULTS.armed


def test_reload_survives_bad_env_spec(monkeypatch):
    monkeypatch.setenv("DTPU_FAULTS", "not a valid spec !!!")
    reload_from_env()  # must not raise
    assert not faults_mod.FAULTS.armed
    monkeypatch.delenv("DTPU_FAULTS")
    reload_from_env()


def test_rule_fires_at_is_pure():
    r = FaultRule(point="p", action="drop", prob=0.4, seed=9)
    first = [r.fires_at(i) for i in range(1, 30)]
    again = [r.fires_at(i) for i in range(1, 30)]
    assert first == again  # memoized decisions never change
