"""Streamed block-wise KV transfer (ISSUE 10 tentpole).

Covers the block-window streaming protocol end to end on the CPU mesh:
the decode-side pull overlapping a still-running prefill (the server waits
on the engine's per-chunk commit signal), per-block retry-then-recompute on
mid-stream faults (DTPU_FAULTS point ``transfer.stream_window``,
same-seed-same-schedule), arena slot lease lifecycle under cancelled and
half-consumed streams, the transfer-cost bandwidth estimator, the
scheduler's extra-cost term, PrefillRouter deflection planning, and the
analytic streamed-vs-blocking TTFT gate (``ops.costs.streamed_transfer_model``).
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.engine.transfer import KvCommitSignal
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.ops.costs import streamed_transfer_model
from dynamo_tpu.runtime.bandwidth import WIRE_PRIORS, WireBandwidthEstimator
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.tokens import compute_sequence_hashes


def tiny_cfg(**kw):
    mcfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
    )
    defaults = dict(
        num_blocks=96, block_size=4, max_batch_size=4, max_context=128,
        # small chunk cap: a 96-token prompt prefills as 3 chunks, so the
        # server commits (and can stream) blocks three times per request
        prefill_buckets=(16, 32),
    )
    defaults.update(kw)
    return TpuEngineConfig(model=mcfg, **defaults)


def preq(rid, tokens, max_tokens=8):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


# ---------------------------------------------------------------------------
# unit layers: commit signal, bandwidth estimator, cost model, scheduler
# ---------------------------------------------------------------------------


async def test_commit_signal_broadcast_and_generation():
    sig = KvCommitSignal()
    # a fire between waits is never lost (generation check)
    g0 = sig.gen
    sig.fire()
    assert await sig.wait(g0, timeout=0.01) == g0 + 1
    # two concurrent waiters both wake on one fire
    g = sig.gen
    r1 = asyncio.create_task(sig.wait(g, timeout=5.0))
    r2 = asyncio.create_task(sig.wait(g, timeout=5.0))
    await asyncio.sleep(0.01)
    sig.fire()
    assert await r1 == g + 1 and await r2 == g + 1
    # timeout returns the unchanged generation
    assert await sig.wait(sig.gen, timeout=0.01) == sig.gen


def test_bandwidth_estimator_priors_and_ewma():
    est = WireBandwidthEstimator(alpha=0.5)
    # unseen wires price at their prior; unknown classes at the default
    assert est.bandwidth("ici") == WIRE_PRIORS["ici"]
    assert est.bandwidth("carrier-pigeon") == WIRE_PRIORS["inline"]
    assert est.transfer_seconds("native", 0) == 0.0
    # first observation replaces the prior outright
    est.observe("native", 10_000_000, 0.01)  # 1e9 B/s
    assert est.bandwidth("native") == pytest.approx(1e9)
    # EWMA folds the next one at alpha
    est.observe("native", 10_000_000, 0.02)  # 5e8 B/s
    assert est.bandwidth("native") == pytest.approx(0.5 * 1e9 + 0.5 * 5e8)
    # degenerate samples are ignored
    est.observe("native", 0, 1.0)
    est.observe("native", 100, 0.0)
    assert est.snapshot()["native"]["observations"] == 2
    assert est.transfer_seconds("native", 7.5e8) == pytest.approx(1.0)


def test_transfer_model_streamed_never_worse_than_blocking():
    """The tier-1 acceptance gate: across a parameter grid the streamed
    pipeline's modeled TTFT never exceeds blocking, and strictly beats it
    whenever there is any transfer to hide under multi-chunk compute."""
    for prompt in (0, 48, 512, 2048, 8192):
        for bw in (2.5e7, 5e8, 2e9, 4e10):
            for chunk_s in (0.005, 0.05, 0.5):
                for window in (1, 8, 64):
                    m = streamed_transfer_model(
                        prompt,
                        block_size=16,
                        prefill_chunk=512,
                        kv_bytes_per_block=2 << 20,
                        bandwidth_bytes_s=bw,
                        prefill_chunk_s=chunk_s,
                        window_blocks=window,
                    )
                    assert m["streamed_ttft_s"] <= m["blocking_ttft_s"], m
                    assert 0.0 <= m["overlap_fraction"] <= 1.0, m
                    if prompt > 512 and m["transfer_s"] > 0:
                        # multi-chunk prefill: early windows MUST hide
                        assert m["streamed_ttft_s"] < m["blocking_ttft_s"], m


def test_scheduler_extra_costs_term():
    from dynamo_tpu.kv_router.protocols import OverlapScores, WorkerWithDpRank
    from dynamo_tpu.kv_router.scheduler import KvScheduler

    a, b = WorkerWithDpRank(1, 0), WorkerWithDpRank(2, 0)
    sched = KvScheduler()
    base = sched.select_worker([a, b], OverlapScores({}), query_blocks=10)
    assert base.worker == a  # tie broken deterministically
    # a slow wire on the tied-best candidate flips the decision
    d = sched.select_worker(
        [a, b], OverlapScores({}), query_blocks=10, extra_costs={a: 5.0}
    )
    assert d.worker == b
    assert d.logits[a] == 15.0 and d.logits[b] == 10.0


def test_prefill_router_plan_deflection_and_wire_cost():
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.prefill_router import DisaggConfig, PrefillRouter

    class _Inst:
        def __init__(self, wire):
            self.metadata = {
                "data_parallel_size": 1,
                "transfer_address": f"tcp://stub/{wire}",
                "kv_wire": wire,
            }

    class _Client:
        instances = {1: _Inst("inline"), 2: _Inst("native")}

    dcfg = DisaggConfig(
        streamed=True, deflect=True, deflect_max_tokens=16,
        deflect_overlap_frac=0.5, deflect_margin=1.0,
        prefill_block_time_s=0.01, kv_bytes_per_block=1 << 20,
    )
    router = PrefillRouter(
        runtime=None,
        card=ModelDeploymentCard(name="m", kv_block_size=4),
        disagg=dcfg,
    )
    router.client = _Client()
    router.bandwidth = WireBandwidthEstimator(
        priors={"native": 1e9, "inline": 1e6}
    )
    # short prompt: never pays the hop
    plan = router.plan(preq("r1", list(range(8))))
    assert plan.deflected and plan.deflect_reason == "short_prompt"
    # decode pool already hot: radix-hit deflection
    long_prompt = list(range(100))  # 25 blocks of 4
    plan = router.plan(preq("r2", long_prompt), decode_overlap_blocks=20)
    assert plan.deflected and plan.deflect_reason == "radix_hit"
    # otherwise: the fast-wire candidate wins on transfer cost alone
    plan = router.plan(preq("r3", long_prompt))
    assert not plan.deflected
    assert plan.worker_id == 2 and plan.wire == "native"
    assert plan.streamed and plan.transfer_address == "tcp://stub/native"
    assert plan.est_transfer_s == pytest.approx(25 * (1 << 20) / 1e9)
    assert len(plan.hashes) == 25
    # a brutally slow wire everywhere makes the hop cost-ineffective:
    # load-skew deflection kicks in
    router.bandwidth = WireBandwidthEstimator(
        priors={"native": 1e5, "inline": 1e5}
    )
    plan = router.plan(preq("r4", long_prompt))
    assert plan.deflected and plan.deflect_reason == "load_skew"


# ---------------------------------------------------------------------------
# the wire protocol on real engines
# ---------------------------------------------------------------------------


@pytest.mark.slow
async def test_streamed_wire_protocol_end_to_end(monkeypatch):
    """One prefill engine, three decode pulls over the streamed wire:

    1. overlap — the pull starts BEFORE the prefill and completes only as
       the prefill's chunks commit (the server waits on the commit signal);
    2. mid-stream fault — an armed ``transfer.stream_window`` drop loses
       the stream after the first window; the client resumes from the first
       missing block and still imports everything (per-block retry), with a
       deterministic fired schedule;
    3. fault exhaustion — persistent drops give up after the resume budget;
       ONLY the un-imported suffix is recomputed (the imported prefix stays
       cached) and greedy output is still byte-identical.
    """
    monkeypatch.setenv("DTPU_ICI_TRANSFER", "0")    # force the wire
    monkeypatch.setenv("DTPU_DEVICE_TRANSFER", "0")
    prompt = list(range(100, 196))  # 96 tokens = 24 blocks = 3 chunks
    hashes = [int(h) for h in compute_sequence_hashes(prompt, 4)]
    prompt_blocks = len(prompt) // 4

    golden = []
    ref = TpuEngine(tiny_cfg())
    try:
        async for out in ref.generate(preq("golden", prompt), Context()):
            golden.extend(out.token_ids)
    finally:
        ref.stop()
    assert len(golden) == 8

    prefill = TpuEngine(tiny_cfg())
    addr = await prefill.serve_transfer()
    try:
        # ---- 1. pull launched BEFORE the prefill ---------------------------
        decode = TpuEngine(tiny_cfg())
        try:
            client = decode._get_transfer_client()
            pull = asyncio.create_task(client.fetch_and_import(
                addr, hashes[:prompt_blocks], stream=True,
            ))
            await asyncio.sleep(0.05)  # stream opens against an empty cache
            assert not pull.done()
            async for _ in prefill.generate(preq("p1", prompt, 1), Context()):
                pass
            tokens = await asyncio.wait_for(pull, timeout=30)
            assert tokens == prompt_blocks * 4  # every committed block shipped
            assert len(decode.allocator.match_prefix(hashes[:prompt_blocks])) \
                == prompt_blocks
            # ... and the decode output over the imported KV is byte-exact
            got, cached = [], None
            req = preq("d1", prompt)
            req.kv_transfer = {"address": addr, "hashes": hashes, "stream": True}
            async for out in decode.generate(req, Context()):
                got.extend(out.token_ids)
                if out.annotations and "cached_tokens" in out.annotations:
                    cached = out.annotations["cached_tokens"]
            assert got == golden
            # admission reuses every block strictly before the last token
            assert cached == ((len(prompt) - 1) // 4) * 4
        finally:
            decode.stop()

        # ---- 2. mid-stream drop: per-block resume --------------------------
        FAULTS.disarm("transfer.stream_window")
        FAULTS.arm("transfer.stream_window:drop@2")
        try:
            decode2 = TpuEngine(tiny_cfg())
            try:
                plan = FAULTS.plan("transfer.stream_window", 8)
                got2 = await decode2._get_transfer_client().fetch_and_import(
                    addr, hashes[:prompt_blocks], stream=True,
                )
                assert got2 == prompt_blocks * 4  # resumed, nothing lost
                fired = [f for f in FAULTS.fired
                         if f[0] == "transfer.stream_window"]
                assert fired == [("transfer.stream_window", "drop", 2)]
                # same-seed-same-schedule: the preview matches what fired
                assert (2, "drop") in plan
            finally:
                decode2.stop()
        finally:
            FAULTS.disarm("transfer.stream_window")

        # ---- 3. exhaustion: recompute ONLY the lost suffix -----------------
        FAULTS.arm("transfer.stream_window:drop@2+")
        try:
            decode3 = TpuEngine(tiny_cfg())
            try:
                req = preq("d3", prompt)
                req.kv_transfer = {
                    "address": addr, "hashes": hashes[:prompt_blocks],
                    "stream": True,
                }
                got3, cached3 = [], None
                async for out in decode3.generate(req, Context()):
                    got3.extend(out.token_ids)
                    if out.annotations and "cached_tokens" in out.annotations:
                        cached3 = out.annotations["cached_tokens"]
                # window 1 (8 blocks) landed before the drops: that prefix
                # is cached; the remaining 16 blocks were recomputed — not
                # the whole request
                assert cached3 == 8 * 4, cached3
                assert got3 == golden
            finally:
                decode3.stop()
        finally:
            FAULTS.disarm("transfer.stream_window")
    finally:
        prefill.stop()


# ---------------------------------------------------------------------------
# arena slot lease lifecycle under streaming
# ---------------------------------------------------------------------------


class _StubAgent:
    port = 1

    def close(self):
        pass


async def _native_stream_server():
    """A transfer server whose native plane is stubbed: real arena + real
    lease table, no C++ agent — exactly the lease lifecycle under test."""
    eng = TpuEngine(tiny_cfg())
    await eng.serve_transfer()
    srv = eng._kv_transfer_srv
    block_elems = srv._block_nbytes // srv._arena_dtype.itemsize
    srv._arena = np.zeros((srv._arena_slots, block_elems), srv._arena_dtype)
    srv._agent = _StubAgent()
    prompt = list(range(200, 296))  # 24 committed blocks after prefill
    async for _ in eng.generate(preq("warm", prompt, 1), Context()):
        pass
    hashes = [int(h) for h in compute_sequence_hashes(prompt, 4)]
    return eng, srv, hashes[: len(prompt) // 4]


async def test_cancelled_stream_releases_window_leases():
    """A client that dies mid-stream must not pin arena slots for the full
    SLOT_LEASE_S — the stream's unfreed leases drop at generator exit."""
    eng, srv, hashes = await _native_stream_server()
    try:
        gen = srv._handle_stream({
            "hashes": hashes, "stream": True, "window": 8, "native_ok": True,
        })
        item = await gen.__anext__()       # first window: 8 slots leased
        assert "native" in item and len(item["native"]["slots"]) == 8
        assert len(srv._slot_lease) == 8
        await gen.aclose()                 # client gone mid-stream
        # every lease the dead stream issued is reclaimed immediately
        assert not srv._slot_lease, srv._slot_lease
    finally:
        eng.stop()


async def test_failed_native_gather_reclaims_leases(monkeypatch):
    """One-shot native serve whose arena gather dies mid-flight must drop
    its leases immediately: the client never learns the slot numbers, so
    nothing else would free them until SLOT_LEASE_S expiry — the stream-
    exit reclaim (PR 10), applied to the blocking branch. Found by the
    analyzer's RESOURCE-LEAK pass."""
    eng, srv, hashes = await _native_stream_server()
    try:
        async def boom(block_ids, slots):
            raise RuntimeError("device gather died")

        monkeypatch.setattr(srv, "_gather_into_arena", boom)
        gen = srv.handle({"hashes": hashes, "native_ok": True}, None)
        with pytest.raises(RuntimeError):
            async for _ in gen:
                pass
        # every lease the failed serve took is reclaimed, and the pinned
        # prefix refs were dropped by the existing finally
        assert not srv._slot_lease, srv._slot_lease
    finally:
        eng.stop()


async def test_clean_stream_keeps_leases_for_client_free():
    """A half-consumed-but-cleanly-finished stream must NOT yank the last
    window's slots out from under the client: leases survive the eof and
    are released by the client's free_slots call (or normal expiry)."""
    eng, srv, hashes = await _native_stream_server()
    try:
        items = []
        gen = srv._handle_stream({
            "hashes": hashes, "stream": True, "window": 8, "native_ok": True,
        })
        async for item in gen:
            items.append(item)
        assert items[-1].get("eof") and items[-1]["served"] == len(hashes)
        windows = [it for it in items if "native" in it]
        assert len(windows) == 3           # 24 blocks / window 8
        # leases still held: the client may be mid-fetch on the last window
        assert len(srv._slot_lease) == 24
        # the client's free_slots releases them (token-checked)
        for it in windows:
            nat = it["native"]
            out = []
            async for resp in srv.handle(
                {"free_slots": nat["slots"], "token": nat["token"]}, None
            ):
                out.append(resp)
            assert out == [{"ok": True}]
        assert not srv._slot_lease
    finally:
        eng.stop()
