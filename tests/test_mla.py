"""MLA (DeepSeek-style multi-head latent attention) family tests.

The load-bearing property: the serving path's absorbed/MQA-over-latent
attention (models/mla.py layer_forward) must reproduce the uncompressed
per-head attention (mla.reference_attention) exactly — that equivalence is
what lets the engine cache 576-float latents instead of full K/V.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import mla, registry
from dynamo_tpu.models.llama import rms_norm, rope_cos_sin
from dynamo_tpu.ops import attention as att
from dynamo_tpu.parallel.mesh import make_mesh
from dynamo_tpu.runtime.engine import Context


def _cfg(**kw):
    return mla.MlaConfig.tiny_mla(**kw)


def _causal_attend(q, k, v, layer_idx):
    return att.causal_attention(q, k, v)


class TestMlaMath:
    def test_absorbed_equals_reference(self):
        """MQA-over-latent == uncompressed per-head MLA attention."""
        for cfg in (_cfg(), _cfg(q_lora_rank=96)):
            p = mla.init_layer_params(jax.random.PRNGKey(0), cfg, layer_idx=0)
            S = 12
            x = jax.random.normal(
                jax.random.PRNGKey(1), (S, cfg.hidden_size), cfg.dtype
            )
            positions = jnp.arange(S, dtype=jnp.int32)
            h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
            ref_delta = mla.reference_attention(p, cfg, h, positions)

            # run layer_forward but isolate the attention residual: zero FFN
            cos, sin = rope_cos_sin(positions, cfg.qk_rope_head_dim, cfg.rope_theta)
            cos, sin = cos[..., None, :], sin[..., None, :]
            p_noffn = dict(p)
            p_noffn["w_down"] = jnp.zeros_like(p["w_down"])
            out = mla.layer_forward(
                p_noffn, cfg, x, cos, sin, _causal_attend, layer_idx=0
            )
            got_delta = out - x
            np.testing.assert_allclose(
                np.asarray(got_delta), np.asarray(ref_delta),
                rtol=2e-4, atol=2e-4,
            )

    def test_cache_layout_is_latent_sized(self):
        cfg = _cfg()
        assert cfg.num_kv_heads == 1
        assert cfg.head_dim == cfg.kv_lora_rank + cfg.qk_rope_head_dim
        # a preset that tries to drift gets re-pinned
        cfg2 = mla.MlaConfig.tiny_mla(num_kv_heads=8, head_dim=999)
        assert cfg2.num_kv_heads == 1
        assert cfg2.head_dim == cfg2.kv_lora_rank + cfg2.qk_rope_head_dim
        # the latent cache is 1-head -> replicated spec, not head-sharded
        assert registry.kv_cache_spec(cfg) == jax.sharding.PartitionSpec(
            None, None, None, None
        )

    def test_moe_layers_route_and_shared_expert_contributes(self):
        cfg = mla.MlaConfig.tiny_mla_moe()
        assert cfg.first_dense_layers == 1
        p = mla.init_params(jax.random.PRNGKey(0), cfg)
        # layer 0 dense (2-D ffn weights), layer >=1 MoE (3-D expert stacks)
        assert p["layers"][0]["w_gate"].ndim == 2
        assert p["layers"][1]["w_egate"].ndim == 3
        assert "w_shared_gate" in p["layers"][1]
        x = jax.random.normal(jax.random.PRNGKey(2), (6, cfg.hidden_size), cfg.dtype)
        topw, topi = mla.route(p["layers"][1], cfg, x)
        # sigmoid scoring + norm + scaling factor: rows sum to the factor
        np.testing.assert_allclose(
            np.asarray(topw.sum(-1)), cfg.routed_scaling_factor, rtol=1e-5
        )
        assert int(topi.max()) < cfg.num_experts
        # zeroing the shared expert changes the output (it is always on)
        y1 = mla._moe_ffn(p["layers"][1], cfg, x)  # noqa: SLF001
        p2 = dict(p["layers"][1])
        p2["w_shared_down"] = jnp.zeros_like(p2["w_shared_down"])
        y2 = mla._moe_ffn(p2, cfg, x)
        assert not np.allclose(np.asarray(y1), np.asarray(y2))

    def test_full_forward_shapes(self):
        for cfg in (_cfg(), mla.MlaConfig.tiny_mla_moe()):
            p = mla.init_params(jax.random.PRNGKey(0), cfg)
            toks = jnp.arange(8, dtype=jnp.int32)
            hidden = mla.forward(p, cfg, toks, toks, _causal_attend)
            assert hidden.shape == (8, cfg.hidden_size)
            logits = mla.lm_logits(p, cfg, hidden)
            assert logits.shape == (8, cfg.vocab_size)


# ------------------------------------------------------------------- engine
def mla_engine(cfg=None, tp=1, **kw):
    mcfg = cfg or _cfg()
    defaults = dict(
        num_blocks=64, block_size=4, max_batch_size=4, max_context=256,
        prefill_buckets=(16, 32, 64, 128, 256), tp=tp,
    )
    defaults.update(kw)
    mesh = make_mesh(tp=tp, devices=jax.devices()[:tp])
    return TpuEngine(TpuEngineConfig(model=mcfg, **defaults), mesh=mesh)


def greedy_req(rid, tokens, max_tokens=8):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=tokens,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling=SamplingOptions(temperature=0.0),
    )


async def _run(engine, req):
    toks = []
    async for out in engine.generate(req, Context()):
        toks.extend(out.token_ids)
    return toks


async def test_engine_serves_mla_greedy_deterministic():
    engine = mla_engine()
    try:
        prompt = list(range(40, 60))
        t1 = await _run(engine, greedy_req("a", prompt))
        t2 = await _run(engine, greedy_req("b", prompt))
        assert len(t1) == 8
        assert t1 == t2
    finally:
        engine.stop()


@pytest.mark.slow
async def test_engine_serves_mla_moe():
    engine = mla_engine(cfg=mla.MlaConfig.tiny_mla_moe())
    try:
        toks = await _run(engine, greedy_req("a", list(range(30, 50))))
        assert len(toks) == 8
    finally:
        engine.stop()


@pytest.mark.slow
async def test_engine_mla_tp2_matches_tp1():
    """TP=2: q heads sharded, latent cache replicated — same greedy tokens
    as single-shard."""
    prompt = list(range(20, 44))
    e1 = mla_engine()
    try:
        t1 = await _run(e1, greedy_req("a", prompt))
    finally:
        e1.stop()
    e2 = mla_engine(tp=2)
    try:
        t2 = await _run(e2, greedy_req("b", prompt))
    finally:
        e2.stop()
    assert t1 == t2


@pytest.mark.slow
async def test_engine_mla_moe_ep_tp2_matches_tp1():
    """MoE MLA under tp=2: expert stacks shard on the expert dim (EP via
    shard_map psum, registry mla_expert_fn) — same greedy tokens as the
    replicated-expert gather path at tp=1."""
    cfg = mla.MlaConfig.tiny_mla_moe()
    prompt = list(range(25, 49))
    e1 = mla_engine(cfg=cfg)
    try:
        t1 = await _run(e1, greedy_req("a", prompt))
    finally:
        e1.stop()
    e2 = mla_engine(cfg=cfg, tp=2)
    try:
        t2 = await _run(e2, greedy_req("b", prompt))
    finally:
        e2.stop()
    assert t1 == t2


def test_kv_cache_spec_gqa_fallback():
    """GQA caches shard kv_heads over TP only when they divide; otherwise
    (and always for 1-head MQA/latent caches) they replicate — matching the
    engine's Pallas eligibility condition."""
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.models.llama import LlamaConfig
    from dynamo_tpu.parallel.mesh import AXIS_TP

    gqa = LlamaConfig(num_kv_heads=2)
    assert registry.kv_cache_spec(gqa, tp=2) == P(None, None, AXIS_TP, None)
    # 2 kv heads on 4 TP shards cannot lay out: replicate
    assert registry.kv_cache_spec(gqa, tp=4) == P(None, None, None, None)


@pytest.mark.slow
async def test_engine_mla_ring_chunked_prefill():
    """MLA + context parallelism: a prompt longer than every prefill bucket
    runs chunked through ring_extend attention on an sp=2 x tp=2 mesh with
    the 1-head latent KV — same greedy output as the plain engine."""
    cfg = _cfg()
    prompt = list(range(100, 250))  # 150 tokens; buckets force 3 chunks
    plain = mla_engine(cfg=cfg, max_context=512, prefill_buckets=(32, 64))
    try:
        want = await _run(plain, greedy_req("a", prompt, max_tokens=2))
    finally:
        plain.stop()
    ring = TpuEngine(
        TpuEngineConfig(
            model=cfg, num_blocks=64, block_size=16, max_batch_size=2,
            max_context=512, prefill_buckets=(32, 64), sp=2, tp=2,
        ),
        mesh=make_mesh(tp=2, sp=2, devices=jax.devices()[:4]),
    )
    try:
        got = await _run(ring, greedy_req("b", prompt, max_tokens=2))
    finally:
        ring.stop()
    assert got == want
