"""Real checkpoint serving: safetensors -> sharded device_put -> tokens out.

Round-3 verdict missing #6: serve a real (HF-format) published-style
checkpoint end-to-end — config.json + model.safetensors + a real fast
tokenizer with a chat template — through hub resolution (llm/hub.py, the
hub.rs analog), weight mapping (engine/weights.py), the warm cache, and the
dynamo-run CLI.

The checkpoint is BUILT here (deterministic tensors, trained-free) because
the image has zero egress; its format is exactly what `save_pretrained`
produces, so the loader paths exercised are the published-checkpoint ones.
"""

import pytest

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

H, L, HEADS, KVH, HEAD_DIM, INTER, VOCAB = 32, 2, 4, 2, 8, 64, 256

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>{{ message['content'] }}"
    "{% endfor %}{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


def build_checkpoint(path: str) -> None:
    """Write a complete tiny HF llama checkpoint: config + safetensors +
    fast tokenizer (real tokenizers-library BPE) + chat template."""
    from safetensors.numpy import save_file
    from tokenizers import Tokenizer
    from tokenizers.models import BPE
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.trainers import BpeTrainer

    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({
            "model_type": "llama",
            "vocab_size": VOCAB,
            "hidden_size": H,
            "num_hidden_layers": L,
            "num_attention_heads": HEADS,
            "num_key_value_heads": KVH,
            "head_dim": HEAD_DIM,
            "intermediate_size": INTER,
            "rope_theta": 10000.0,
            "rms_norm_eps": 1e-6,
            "max_position_embeddings": 512,
            "tie_word_embeddings": False,
        }, f)

    rng = np.random.default_rng(42)

    def w(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w(VOCAB, H),
        "model.norm.weight": np.ones(H, np.float32),
        "lm_head.weight": w(VOCAB, H),
    }
    q = HEADS * HEAD_DIM
    kv = KVH * HEAD_DIM
    for i in range(L):
        p = f"model.layers.{i}."
        tensors.update({
            p + "input_layernorm.weight": np.ones(H, np.float32),
            p + "post_attention_layernorm.weight": np.ones(H, np.float32),
            p + "self_attn.q_proj.weight": w(q, H),
            p + "self_attn.k_proj.weight": w(kv, H),
            p + "self_attn.v_proj.weight": w(kv, H),
            p + "self_attn.o_proj.weight": w(H, q),
            p + "mlp.gate_proj.weight": w(INTER, H),
            p + "mlp.up_proj.weight": w(INTER, H),
            p + "mlp.down_proj.weight": w(H, INTER),
        })
    save_file(tensors, os.path.join(path, "model.safetensors"))

    # a REAL trained BPE tokenizer (tiny corpus), saved the HF-fast way
    tok = Tokenizer(BPE(unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    trainer = BpeTrainer(
        vocab_size=VOCAB,
        special_tokens=["<unk>", "<s>", "</s>", "<|user|>", "<|assistant|>"],
    )
    corpus = ["hello world how are you today",
              "the quick brown fox jumps over the lazy dog",
              "tell me a story about tpus serving tokens"]
    tok.train_from_iterator(corpus, trainer)
    tok.save(os.path.join(path, "tokenizer.json"))
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "unk_token": "<unk>", "bos_token": "<s>", "eos_token": "</s>",
            "chat_template": CHAT_TEMPLATE,
        }, f)


def test_hub_resolution(tmp_path):
    from dynamo_tpu.llm.hub import resolve_model_path

    # 1. a local directory resolves to itself
    local = tmp_path / "ckpt"
    build_checkpoint(str(local))
    assert resolve_model_path(str(local)) == str(local)

    # 2. HF cache layout with refs/main
    cache = tmp_path / "hub"
    repo = cache / "models--acme--tiny-llama"
    snap = repo / "snapshots" / "abc123"
    snap.mkdir(parents=True)
    (repo / "refs").mkdir()
    (repo / "refs" / "main").write_text("abc123")
    assert resolve_model_path("acme/tiny-llama", cache_dir=str(cache)) == str(snap)

    # 3. offline miss is an actionable error
    os.environ["DTPU_HUB_OFFLINE"] = "1"
    try:
        import pytest

        with pytest.raises(FileNotFoundError, match="offline"):
            resolve_model_path("acme/absent", cache_dir=str(cache))
    finally:
        del os.environ["DTPU_HUB_OFFLINE"]


def test_weight_mapping_roundtrip(tmp_path):
    """load_params maps HF [out,in] Linears onto our [in,out] pytree."""
    from safetensors import safe_open

    from dynamo_tpu.engine.weights import config_from_hf, load_params

    path = str(tmp_path / "ckpt")
    build_checkpoint(path)
    cfg = config_from_hf(path)
    assert cfg.num_layers == L and cfg.num_kv_heads == KVH
    params = load_params(path, cfg)
    with safe_open(os.path.join(path, "model.safetensors"), framework="np") as f:
        wq_hf = f.get_tensor("model.layers.0.self_attn.q_proj.weight")
        embed_hf = f.get_tensor("model.embed_tokens.weight")
    # params load in the model dtype (bf16): cast the HF side identically
    # and demand EXACT equality — transposition or row/col mixups would
    # produce large diffs, rounding produces none
    dt = np.asarray(params["layers"][0]["wq"]).dtype
    np.testing.assert_array_equal(
        np.asarray(params["layers"][0]["wq"]), wq_hf.T.astype(dt)
    )
    np.testing.assert_array_equal(
        np.asarray(params["embed"]), embed_hf.astype(dt)
    )


@pytest.mark.slow
def test_serve_real_checkpoint_e2e(tmp_path):
    """dynamo-run serves the checkpoint: hub resolve -> warm load -> chat
    template -> generate -> detokenize. The complete published-checkpoint
    serving path in one process."""
    ckpt = str(tmp_path / "ckpt")
    build_checkpoint(ckpt)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dtpu_jax_cache")
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run",
         "in=text:hello world", f"out={ckpt}",
         "--platform", "cpu", "--max-tokens", "4"],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert r.stdout.strip(), "no generated text"


@pytest.mark.slow
def test_serve_hub_reference_e2e(tmp_path):
    """Same, but the model is addressed as 'org/name' through a hub cache."""
    cache = tmp_path / "hub"
    repo = cache / "models--acme--tiny-llama"
    snap = repo / "snapshots" / "rev0"
    build_checkpoint(str(snap))
    (repo / "refs").mkdir(parents=True)
    (repo / "refs" / "main").write_text("rev0")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DTPU_HUB_CACHE"] = str(cache)
    env["DTPU_HUB_OFFLINE"] = "1"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dtpu_jax_cache")
    r = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.run",
         "in=text:hello world", "out=acme/tiny-llama",
         "--platform", "cpu", "--max-tokens", "4"],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert r.stdout.strip(), "no generated text"
