"""Regressions for the cross-plane contract defects CONTRACT-DRIFT surfaced.

Each of these was a real producer/consumer drift on the live tree: the
``evacuation`` plan consumed by migration but produced nowhere, the global
router reading SLA annotation keys nothing stamps, the ``worker_id``
first-chunk attribution documented but never wired into the flight
recorder, the image endpoint swallowing error-finish frames into a 200,
and TensorRequest decoding mis-routed payloads instead of failing on the
``op`` discriminator it writes.
"""

import asyncio
import time
import types

import pytest

from dynamo_tpu.global_router import GlobalRouterConfig, GlobalRouterHandler
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    PreprocessedRequest,
)
from dynamo_tpu.llm.protocols.tensor import Tensor, TensorRequest
from dynamo_tpu.runtime.flight_recorder import (
    FlightRecorder,
    set_flight_recorder,
)


# -- evacuation plan: the error-finish frame's kv_transfer reference ----------

class _Seq:
    def __init__(self, hashes):
        self._h = hashes

    def sequence_hashes(self):
        return list(self._h)


def _engine(transfer_address="10.0.0.7:7001", block_size=16,
            bytes_per_block=4096):
    from dynamo_tpu.engine.engine import TpuEngine

    eng = types.SimpleNamespace(
        transfer_address=transfer_address,
        cfg=types.SimpleNamespace(block_size=block_size),
        kv_bytes_per_block=bytes_per_block,
    )
    return TpuEngine, eng


def _st(n_prompt=29, produced=3, hashes=(11, 22, 33), no_cache=False):
    return types.SimpleNamespace(
        no_cache=no_cache,
        seq=_Seq(hashes),
        produced=produced,
        req=types.SimpleNamespace(token_ids=list(range(n_prompt))),
    )


def test_evacuation_plan_carries_migration_contract():
    TpuEngine, eng = _engine()
    plan = TpuEngine._evacuation_plan(eng, _st())
    # 29 prompt + 3 produced = 32 tokens -> 2 full blocks of 16; only the
    # 3 sealed hashes' first 2 ride along
    assert plan == {
        "address": "10.0.0.7:7001",
        "hashes": [11, 22],
        "num_tokens": 32,
        "tier": True,
        "bytes_per_block": 4096,
    }
    # exactly the keys discovery._evacuation_costs and migration's replay
    # read — a hole here is the consumed-but-never-produced bug again
    assert {"address", "hashes", "num_tokens", "bytes_per_block"} <= set(plan)


def test_evacuation_plan_none_when_nothing_fetchable():
    TpuEngine, eng = _engine()
    # sub-block progress: no sealed block to evacuate
    assert TpuEngine._evacuation_plan(eng, _st(n_prompt=3, produced=0)) is None
    # request opted out of caching
    assert TpuEngine._evacuation_plan(eng, _st(no_cache=True)) is None
    # no transfer server to serve the pull
    TpuEngine, cold = _engine(transfer_address=None)
    assert TpuEngine._evacuation_plan(cold, _st()) is None


# -- global router: SLA targets come from the sla annotation ------------------

def _router_config():
    return GlobalRouterConfig.from_obj({
        "prefill_pools": ["pf", "ps"],
        "decode_pools": ["fast", "bulk"],
        "prefill_selection": {
            "ttft_min": 0, "ttft_max": 100, "ttft_resolution": 2,
            "isl_min": 0, "isl_max": 4096, "isl_resolution": 1,
            "prefill_pool_mapping": [[0, 1]],
        },
        "decode_selection": {
            "itl_min": 0, "itl_max": 40, "itl_resolution": 2,
            "context_length_min": 0, "context_length_max": 4096,
            "context_length_resolution": 1,
            "decode_pool_mapping": [[0, 1]],
        },
        "default_itl_ms": 35.0,
    })


def _preq(annotations=None):
    return PreprocessedRequest(
        request_id="r1", model="m", token_ids=list(range(8)),
        annotations=annotations or {},
    )


def test_pick_pool_reads_sla_annotation():
    handler = GlobalRouterHandler(None, _router_config())
    # tight itl target (5ms) -> low-latency pool; loose (35ms) -> bulk
    tight = _preq({"sla": {"itl_target_s": 0.005}})
    loose = _preq({"sla": {"itl_target_s": 0.035}})
    assert handler._pick_pool(tight).namespace == "fast"
    assert handler._pick_pool(loose).namespace == "bulk"


def test_pick_pool_defaults_without_sla_annotation():
    handler = GlobalRouterHandler(None, _router_config())
    # no sla annotation: default_itl_ms=35 lands in the loose bucket
    assert handler._pick_pool(_preq()).namespace == "bulk"


def test_pick_pool_prefill_reads_ttft_target():
    handler = GlobalRouterHandler(None, _router_config())
    tight = _preq({"disagg": "prefill", "sla": {"ttft_target_s": 0.010}})
    loose = _preq({"disagg": "prefill", "sla": {"ttft_target_s": 0.090}})
    assert handler._pick_pool(tight).namespace == "pf"
    assert handler._pick_pool(loose).namespace == "ps"


# -- frontend: worker attribution lands on the flight timeline ----------------

async def test_observed_records_worker_attribution():
    from dynamo_tpu.llm import ModelManager
    from dynamo_tpu.llm.http.service import HttpService

    rec = FlightRecorder(capacity=8)
    set_flight_recorder(rec)
    try:
        svc = HttpService(ModelManager(), host="127.0.0.1", port=0)

        async def stream():
            # first chunk carries the engine's metrics annotations,
            # including the router-stamped serving worker
            yield BackendOutput(
                token_ids=[1],
                annotations={"worker_id": 7, "prefill_worker_id": 9},
            )
            yield BackendOutput(token_ids=[2], finish_reason="stop")

        outs = [
            o async for o in svc._observed(
                stream(), "m", time.monotonic(), request_id="r-attr"
            )
        ]
        assert len(outs) == 2
        events = [e["event"] for e in rec.timeline("r-attr")["events"]]
        by_kind = {e["kind"]: e for e in events}
        assert by_kind["first_token"]["worker_id"] == 7
        assert by_kind["prefill_done"]["prefill_worker_id"] == 9
    finally:
        set_flight_recorder(None)


async def test_observed_omits_worker_id_when_engine_does_not_echo():
    from dynamo_tpu.llm import ModelManager
    from dynamo_tpu.llm.http.service import HttpService

    rec = FlightRecorder(capacity=8)
    set_flight_recorder(rec)
    try:
        svc = HttpService(ModelManager(), host="127.0.0.1", port=0)

        async def stream():
            yield BackendOutput(token_ids=[1], finish_reason="stop")

        [o async for o in svc._observed(
            stream(), "m", time.monotonic(), request_id="r-plain"
        )]
        events = [e["event"] for e in rec.timeline("r-plain")["events"]]
        first = next(e for e in events if e["kind"] == "first_token")
        assert "worker_id" not in first  # no None pollution
    finally:
        set_flight_recorder(None)


# -- image endpoint: error-finish frames surface as 502, not empty 200 --------

class _BoomImageEngine:
    async def generate(self, request, context):
        yield BackendOutput(
            finish_reason="error",
            annotations={"error": "sampler exploded"},
        ).to_obj()


async def test_images_error_frame_surfaces_502():
    import aiohttp

    from dynamo_tpu.llm import (
        ModelDeploymentCard,
        ModelManager,
        ModelWatcher,
        register_llm,
    )
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.runtime import (
        DistributedRuntime,
        InProcEventPlane,
        MemKVStore,
        RouterMode,
        RuntimeConfig,
    )

    store = MemKVStore()

    def make_rt():
        cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=2.0)
        return DistributedRuntime(
            cfg, store=store, event_plane=InProcEventPlane()
        )

    worker_rt = await make_rt().start()
    frontend_rt = await make_rt().start()
    card = ModelDeploymentCard(
        name="boom-images", tokenizer="byte", model_type=["images"],
    )
    served = await register_llm(
        worker_rt, _BoomImageEngine(), card, raw_token_stream=True
    )
    manager = ModelManager()
    watcher = await ModelWatcher(
        frontend_rt, manager, RouterMode.ROUND_ROBIN
    ).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        for _ in range(100):
            p = manager.get("boom-images")
            if p and p.client.instances:
                break
            await asyncio.sleep(0.05)
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{service.port}/v1/images/generations",
                json={"model": "boom-images", "prompt": "x", "n": 1},
            )
            body = await r.json()
        assert r.status == 502
        assert "sampler exploded" in body["error"]["message"]
    finally:
        await service.stop()
        await watcher.stop()
        await served.stop()
        await worker_rt.shutdown()
        await frontend_rt.shutdown()


# -- tensor protocol: the op discriminator round-trips and rejects ------------

def test_tensor_request_op_discriminator():
    import numpy as np

    req = TensorRequest(
        request_id="t1", model="m",
        tensors=[Tensor.from_numpy("x", np.arange(4, dtype=np.float32))],
    )
    obj = req.to_obj()
    assert obj["op"] == "tensor"
    back = TensorRequest.from_obj(obj)
    assert back.request_id == "t1"
    assert back.tensor("x").to_numpy().tolist() == [0.0, 1.0, 2.0, 3.0]
    # a mis-routed chat payload must fail loudly, not decode to empty
    with pytest.raises(ValueError, match="not a tensor request"):
        TensorRequest.from_obj({"op": "chat", "id": "t2", "model": "m"})
    # absent op defaults to tensor (pre-discriminator senders)
    legacy = TensorRequest.from_obj({"id": "t3", "model": "m"})
    assert legacy.request_id == "t3"
