"""Chaos matrix: e2e stacks under armed, deterministic fault injection.

Acceptance for the resilience plane (ISSUE 1): across the matrix the tests
arm four distinct fault points — ``request_plane.send``,
``discovery.lease_keepalive``, ``transfer.pull``, ``event_plane.publish`` —
and prove that

- the same seed produces an identical fault schedule (run-to-run),
- every in-flight request either completes via retry/migration or fails
  with a typed error within its deadline (never hangs),
- circuit-breaker trip/reset is observable through the frontend /metrics.

The stacks reuse the existing e2e harness shapes: the in-process frontend
stack (tests/test_frontend_e2e.py) and the disagg KV-transfer pair
(tests/test_disagg.py).
"""

import pytest

import asyncio

import aiohttp
import jax.numpy as jnp

from dynamo_tpu.llm import (
    EchoEngine,
    ModelDeploymentCard,
    ModelManager,
    ModelWatcher,
    register_llm,
)
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.runtime import (
    DistributedRuntime,
    InProcEventPlane,
    MemKVStore,
    RouterMode,
    RuntimeConfig,
)
from dynamo_tpu.runtime.faults import FAULTS

MODEL = "chaos-model"


def make_rt(store, plane=None, lease_ttl_s=2.0):
    cfg = RuntimeConfig(store="mem", event_plane="inproc", lease_ttl_s=lease_ttl_s)
    return DistributedRuntime(
        cfg, store=store, event_plane=plane or InProcEventPlane()
    )


async def start_stack(n_workers=2, migration_limit=3, lease_ttl_s=2.0):
    store = MemKVStore()
    worker_rts, serveds = [], []
    for i in range(n_workers):
        rt = await make_rt(store, lease_ttl_s=lease_ttl_s).start()
        card = ModelDeploymentCard(
            name=MODEL, tokenizer="byte", context_length=4096,
            migration_limit=migration_limit,
        )
        serveds.append(await register_llm(rt, EchoEngine(), card))
        worker_rts.append(rt)
    frontend_rt = await make_rt(store).start()
    manager = ModelManager()
    watcher = await ModelWatcher(frontend_rt, manager, RouterMode.ROUND_ROBIN).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    for _ in range(200):
        entry = manager.get(MODEL)
        if entry and len(entry.client.instances) == n_workers:
            break
        await asyncio.sleep(0.05)
    else:
        raise AssertionError("workers never discovered")
    base = f"http://127.0.0.1:{service.port}"
    return worker_rts, serveds, frontend_rt, watcher, service, base


async def stop_stack(worker_rts, serveds, frontend_rt, watcher, service):
    await service.stop()
    await watcher.stop()
    for s in serveds:
        await s.stop()
    for rt in worker_rts:
        await rt.shutdown()
    await frontend_rt.shutdown()


async def _chat(session, base, text="hello chaos", deadline=10.0):
    """One request bounded by a hard deadline: a hang fails the test, it
    never wedges the suite."""
    async def go():
        r = await session.post(
            f"{base}/v1/chat/completions",
            json={
                "model": MODEL,
                "messages": [{"role": "user", "content": text}],
                "max_tokens": 8,
            },
        )
        body = await r.json()
        return r.status, r.headers, body

    return await asyncio.wait_for(go(), timeout=deadline)


# -- request plane drops: retry/migration or typed failure, never a hang -----

async def _drive_requests(n=10):
    stack = await start_stack(n_workers=2, migration_limit=3)
    *handles, base = stack
    statuses = []
    try:
        async with aiohttp.ClientSession() as s:
            for i in range(n):
                status, _headers, body = await _chat(s, base, f"req {i}")
                statuses.append(status)
                if status != 200:
                    # failure must be TYPED (the OpenAI error envelope with a
                    # service_unavailable classification), not a raw 500 from
                    # an unhandled injected exception
                    assert status == 503, body
                    assert body["error"]["type"] == "service_unavailable", body
    finally:
        await stop_stack(*handles)
    return statuses


async def test_chaos_request_plane_drop_schedule_is_deterministic():
    """Same seed => identical fault schedule AND identical outcome vector,
    across two full stack incarnations; a different seed diverges."""
    runs = []
    for seed in (7, 7, 8):
        FAULTS.disarm()
        FAULTS.arm(f"request_plane.send:drop@p=0.4@seed={seed}")
        try:
            statuses = await _drive_requests(n=10)
        finally:
            fired = list(FAULTS.fired)
            FAULTS.disarm()
        runs.append((fired, statuses))
        assert any(st == 200 for st in statuses)  # migration keeps serving
    assert runs[0] == runs[1], "same seed must replay the same schedule"
    assert runs[0][0] != runs[2][0], "different seed must differ"
    assert runs[0][0], "the armed fault never fired"


async def test_chaos_request_plane_total_loss_fails_typed():
    """drop on EVERY send + no migration budget: every request fails fast
    with a typed 503 — none hang, none surface a raw injected exception."""
    FAULTS.disarm()
    FAULTS.arm("request_plane.send:drop@1+")
    try:
        stack = await start_stack(n_workers=1, migration_limit=0)
        *handles, base = stack
        try:
            async with aiohttp.ClientSession() as s:
                for i in range(3):
                    status, _h, body = await _chat(s, base, f"doomed {i}")
                    assert status == 503, body
                    assert body["error"]["type"] == "service_unavailable"
        finally:
            await stop_stack(*handles)
    finally:
        FAULTS.disarm()


# -- lease keepalive loss: re-acquire + re-register, service keeps serving ---

async def test_chaos_lease_keepalive_loss_recovers():
    FAULTS.disarm()
    stack = await start_stack(n_workers=1, migration_limit=0, lease_ttl_s=1.0)
    worker_rts, serveds, frontend_rt, watcher, service, base = stack
    try:
        lease_before = worker_rts[0].lease_id
        FAULTS.arm("discovery.lease_keepalive:fail@1+")
        # several heartbeat intervals under failing keepalives: the loop must
        # re-acquire a fresh lease and re-register the served endpoints
        # instead of dying silently
        for _ in range(100):
            await asyncio.sleep(0.05)
            if worker_rts[0].lease_id != lease_before:
                break
        assert worker_rts[0].lease_id != lease_before, "lease never re-acquired"
        FAULTS.disarm()
        await asyncio.sleep(1.0)  # settle: healthy beats, re-registration
        async with aiohttp.ClientSession() as s:
            status = None
            for _ in range(20):
                status, _h, _b = await _chat(s, base)
                if status == 200:
                    break
                await asyncio.sleep(0.2)
            assert status == 200, "service did not recover after lease loss"
    finally:
        FAULTS.disarm()
        await stop_stack(worker_rts, serveds, frontend_rt, watcher, service)


# -- event plane: dropped publishes degrade, never crash the publisher -------

async def test_chaos_event_publish_drops_degrade():
    FAULTS.disarm()
    plane = InProcEventPlane()
    sub = await plane.subscribe("chaos.")
    FAULTS.arm("event_plane.publish:drop@p=0.5@seed=3")
    try:
        for i in range(30):
            # must NOT raise: drops are absorbed and logged
            await plane.publish("chaos.topic", b"payload-%d" % i)
        dropped = sum(1 for p, a, _ in FAULTS.fired if a == "drop")
        assert 0 < dropped < 30
        got = 0
        while True:
            item = await sub.next(timeout=0.1)
            if item is None:
                break
            got += 1
        assert got == 30 - dropped  # the survivors all landed
    finally:
        FAULTS.disarm()
    # disarmed: delivery is whole again
    await plane.publish("chaos.topic", b"after")
    assert (await sub.next(timeout=1.0)) is not None
    await plane.close()


# -- KV transfer pull: retry absorbs a blip; total loss recomputes -----------

def _tiny_engine_cfg():
    from dynamo_tpu.engine.engine import TpuEngineConfig
    from dynamo_tpu.models.llama import LlamaConfig

    mcfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, dtype=jnp.float32,
    )
    return TpuEngineConfig(
        model=mcfg, num_blocks=64, block_size=4, max_batch_size=4,
        max_context=128, prefill_buckets=(16, 32, 64, 128),
    )


@pytest.mark.slow
async def test_chaos_transfer_pull_retry_then_recompute(monkeypatch):
    """One prefill/decode engine pair (the tests/test_disagg.py wire
    harness), two armed phases on distinct prompts:

      phase 1 — ``transfer.pull:drop@1``: the first wire fetch dies, the
      shared policy's retry lands the KV (imported, token-identical);
      phase 2 — ``transfer.pull:drop@1+``: every fetch and retry dies, the
      decode side recomputes the prefill locally (nothing imported, output
      still token-identical, no hang, no surfaced transport error)."""
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import Context
    from dynamo_tpu.tokens import compute_sequence_hashes

    monkeypatch.setenv("DTPU_ICI_TRANSFER", "0")     # force the wire path
    monkeypatch.setenv("DTPU_DEVICE_TRANSFER", "0")

    def preq(rid, tokens, max_tokens=8):
        return PreprocessedRequest(
            request_id=rid, model=MODEL, token_ids=tokens,
            stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )

    async def run(engine, req):
        toks, cached = [], None
        async for out in engine.generate(req, Context()):
            toks.extend(out.token_ids)
            if out.annotations and "cached_tokens" in out.annotations:
                cached = out.annotations["cached_tokens"]
        return toks, cached

    FAULTS.disarm()
    prefill = TpuEngine(_tiny_engine_cfg())
    decode = TpuEngine(_tiny_engine_cfg())
    try:
        addr = await prefill.serve_transfer()
        for phase, (spec, prompt) in enumerate([
            ("transfer.pull:drop@1", list(range(100, 130))),
            ("transfer.pull:drop@1+", list(range(300, 330))),
        ]):
            # the golden run doubles as the prefill-side cache fill (its
            # prompt-prefix pages are exactly what the decode side pulls)
            ref, _ = await run(prefill, preq(f"ref{phase}", prompt))
            assert len(ref) == 8
            FAULTS.arm(spec)
            try:
                hashes = [int(h) for h in compute_sequence_hashes(prompt, 4)]
                req = preq(f"d{phase}", prompt)
                req.kv_transfer = {"address": addr, "hashes": hashes}
                toks, cached = await run(decode, req)
                assert FAULTS.fired, "fault never exercised"
                if phase == 0:
                    assert cached and cached > 0, "retry should import the KV"
                else:
                    assert not cached  # total loss: recomputed instead
                assert toks == ref
            finally:
                FAULTS.disarm()
    finally:
        prefill.stop()
        decode.stop()


# -- circuit breaker: trip + Retry-After + reset, all visible on /metrics ----

async def test_chaos_circuit_breaker_trip_and_reset_via_metrics(monkeypatch):
    monkeypatch.setenv("DTPU_CB_FRONTEND", "threshold=3,rate=0.5,window=5,reset=0.5")
    FAULTS.disarm()
    stack = await start_stack(n_workers=1, migration_limit=0)
    *handles, base = stack
    service = handles[-1]
    try:
        FAULTS.arm("request_plane.send:drop@1+")
        async with aiohttp.ClientSession() as s:
            for i in range(3):  # three worker-loss 503s trip the breaker
                status, headers, _b = await _chat(s, base, f"trip {i}")
                assert status == 503
            # open circuit: shed immediately with Retry-After
            status, headers, body = await _chat(s, base, "shed")
            assert status == 503
            assert "Retry-After" in headers, dict(headers)
            assert "circuit open" in body["error"]["message"]
            metrics = (await (await s.get(f"{base}/metrics")).text())
            assert 'dtpu_circuit_transitions_total' in metrics
            assert 'state="open"' in metrics and 'policy="frontend.%s"' % MODEL in metrics
            # heal the plane, wait out the reset window: the half-open probe
            # closes the circuit and serving resumes
            FAULTS.disarm()
            await asyncio.sleep(0.6)
            status, _h, _b = await _chat(s, base, "probe")
            assert status == 200
            metrics = (await (await s.get(f"{base}/metrics")).text())
            assert 'state="closed"' in metrics
            status, _h, _b = await _chat(s, base, "steady")
            assert status == 200
    finally:
        FAULTS.disarm()
        await stop_stack(*handles)
