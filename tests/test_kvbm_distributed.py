"""Distributed KVBM fleet + block layouts (kvbm/distributed.py, layout.py).

Reference analogs: block_manager/distributed (leader/worker sharding) and
block_manager/layout.rs (FullyContiguous vs LayerSeparate).
"""

import asyncio

import numpy as np

from dynamo_tpu.kvbm.distributed import (
    DistributedBlockPool,
    HashRing,
    register_store,
)
from dynamo_tpu.kvbm.layout import (
    BlockShape,
    FullyContiguous,
    LayerSeparate,
    convert,
    make_layout,
)
from dynamo_tpu.kvbm.remote import RemoteBlockStoreServer
from dynamo_tpu.runtime import MemKVStore


# ------------------------------------------------------------------- layouts
class TestLayouts:
    def setup_method(self):
        self.shape = BlockShape(
            num_layers=3, block_size=4, num_kv_heads=2, head_dim=8,
            dtype=np.dtype(np.float32),
        )
        rng = np.random.default_rng(0)
        self.per_layer = [
            rng.standard_normal(self.shape.layer_shape).astype(np.float32)
            for _ in range(3)
        ]

    def test_contiguous_roundtrip(self):
        fc = FullyContiguous(self.shape)
        block = fc.pack(self.per_layer)
        assert block.shape == self.shape.logical_shape
        raw = fc.to_bytes(block)
        assert len(raw) == self.shape.nbytes
        back = fc.from_bytes(raw)
        np.testing.assert_array_equal(back, block)
        np.testing.assert_array_equal(
            fc.layer_view(block, 1), self.per_layer[1]
        )

    def test_layer_separate_roundtrip(self):
        ls = LayerSeparate(self.shape)
        block = ls.pack(self.per_layer)
        assert len(block) == 3  # no transpose/stack happened
        raw = ls.to_bytes(block)
        assert len(raw) == self.shape.nbytes
        back = ls.from_bytes(raw)
        for a, b in zip(back, self.per_layer):
            np.testing.assert_array_equal(a, b)

    def test_convert_between_layouts(self):
        fc, ls = FullyContiguous(self.shape), LayerSeparate(self.shape)
        block_fc = fc.pack(self.per_layer)
        block_ls = convert(block_fc, fc, ls)
        np.testing.assert_array_equal(block_ls[2], self.per_layer[2])
        back = convert(block_ls, ls, fc)
        np.testing.assert_array_equal(back, block_fc)
        # wire equivalence: both layouts serialize to the same bytes
        assert fc.to_bytes(block_fc) == ls.to_bytes(block_ls)

    def test_factory(self):
        assert isinstance(make_layout("fc", self.shape), FullyContiguous)
        assert isinstance(make_layout("layer_separate", self.shape), LayerSeparate)


# ---------------------------------------------------------------------- ring
def test_ring_balance_and_stability():
    ring = HashRing()
    for a in ("w1:1", "w2:1", "w3:1"):
        ring.add(a)
    owners = {h: ring.owner(h) for h in range(10_000)}
    counts = {}
    for o in owners.values():
        counts[o] = counts.get(o, 0) + 1
    # vnodes keep shards within a loose balance band
    assert all(c > 1500 for c in counts.values()), counts
    # removing one member only moves ITS keys
    ring.remove("w2:1")
    moved = sum(
        1 for h, o in owners.items()
        if o != "w2:1" and ring.owner(h) != o
    )
    assert moved == 0


# -------------------------------------------------------------------- fleet
async def test_fleet_shards_and_survives_member_loss():
    store = MemKVStore()
    s1 = RemoteBlockStoreServer(host="127.0.0.1", port=0, capacity_bytes=1 << 22)
    s2 = RemoteBlockStoreServer(host="127.0.0.1", port=0, capacity_bytes=1 << 22)
    a1, a2 = await s1.start(), await s2.start()
    await register_store(store, "ns", a1, None)
    await register_store(store, "ns", a2, None)
    pool = await DistributedBlockPool(store, "ns").start()
    loop = asyncio.get_event_loop()

    # RemoteBlockPool sockets are BLOCKING (they live on engine offload
    # threads in production); the in-process servers share this event loop,
    # so every pool op must run off-loop here
    async def p_store(h, b):
        await loop.run_in_executor(None, pool.store, h, b)

    async def p_get(h):
        return await loop.run_in_executor(None, pool.get, h)

    async def p_contains_many(hs):
        return await loop.run_in_executor(None, pool.contains_many, hs)

    try:
        for _ in range(100):
            if len(pool.members()) == 2:
                break
            await asyncio.sleep(0.02)
        assert pool.members() == sorted([a1, a2])

        rng = np.random.default_rng(1)
        blocks = {
            h: rng.standard_normal((2, 2, 4)).astype(np.float32)
            for h in range(1000, 1032)
        }
        for h, b in blocks.items():
            await p_store(h, b)
        # sharded across BOTH stores
        n1, n2 = len(s1._blocks), len(s2._blocks)
        assert n1 + n2 == 32 and n1 > 0 and n2 > 0

        for h, b in blocks.items():
            got = await p_get(h)
            np.testing.assert_array_equal(got, b)
        have = await p_contains_many(list(blocks) + [9999])
        assert have[:-1] == [True] * 32 and have[-1] is False

        # member loss: deregister + stop s1 — its shard misses cleanly,
        # s2's shard still serves
        await store.delete(f"v1/kvbm/ns/{a1}")
        await s1.stop()
        for _ in range(100):
            if len(pool.members()) == 1:
                break
            await asyncio.sleep(0.02)
        served = 0
        for h in blocks:
            if (await p_get(h)) is not None:
                served += 1
        assert served == n2  # exactly the surviving store's blocks
    finally:
        await pool.stop()
        await s2.stop()
        try:
            await s1.stop()
        except Exception:
            pass
        await store.close()
