"""Native transfer plane (NIXL analog): host-staging KV block movement.

C++ agent in native/transfer/agent.cpp, loaded via ctypes (the image has no
pybind11). See ``native.py`` for the Python surface.
"""

from .native import NativeAgent, ensure_native, native_available, native_fetch

__all__ = ["NativeAgent", "ensure_native", "native_available", "native_fetch"]
