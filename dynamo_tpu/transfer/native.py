"""ctypes surface over the C++ transfer agent (native/transfer/agent.cpp).

The library builds on demand with `make -C native` (g++ is in the image;
pybind11 is not, hence the C ABI + ctypes). Everything degrades gracefully:
``native_available()`` is False when the toolchain or build is missing and
callers fall back to the Python request-plane transfer path.

Blocking native calls (`dtpu_fetch`) release the GIL for their full duration
(ctypes does this for foreign calls), so multi-MB fetches run concurrently
with the engine loop.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from ..runtime.faults import FAULTS
from ..runtime.logging import get_logger

log = get_logger("transfer.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libdtpu_transfer.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False
_build_thread: Optional[threading.Thread] = None
# arenas whose agent teardown leaked its threads: kept alive forever so the
# leaked writev path can never read freed memory
_LEAKED_ARENAS: list = []


def _build() -> bool:
    global _build_failed
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception as e:
        log.warning("native transfer build failed (%s); using python path", e)
        _build_failed = True
        return False


def _load(build: bool = True) -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB_PATH):
            if not build or not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.warning("native transfer load failed (%s); using python path", e)
            _build_failed = True
            return None
        lib.dtpu_agent_new.restype = ctypes.c_void_p
        lib.dtpu_agent_new.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dtpu_agent_port.restype = ctypes.c_int
        lib.dtpu_agent_port.argtypes = [ctypes.c_void_p]
        lib.dtpu_agent_register.restype = ctypes.c_int
        lib.dtpu_agent_register.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.dtpu_agent_unregister.restype = ctypes.c_int
        lib.dtpu_agent_unregister.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dtpu_agent_free.restype = ctypes.c_int  # 0 freed, 1 leaked
        lib.dtpu_agent_free.argtypes = [ctypes.c_void_p]
        lib.dtpu_fetch.restype = ctypes.c_longlong
        lib.dtpu_fetch.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    """True iff the native library is usable NOW. Never blocks the caller on
    a compile: when the .so is missing, the build is kicked off on a daemon
    thread and this returns False until it lands (async paths — the engine
    loop, request handlers — must not stall ~seconds on `make`)."""
    global _build_thread
    if _load(build=False) is not None:
        return True
    if _build_failed:
        return False
    with _lib_lock:
        if _build_thread is None or not _build_thread.is_alive():
            _build_thread = threading.Thread(target=_build, daemon=True)
            _build_thread.start()
    return False


def ensure_native(timeout_s: float = 120.0) -> bool:
    """Blocking variant for process startup / tests: build + load."""
    del timeout_s
    return _load(build=True) is not None


class NativeAgent:
    """Serving side: registered host arenas exposed over raw TCP.

    An arena is a contiguous numpy buffer sliced into equal-size blocks; the
    agent serves scatter/gather reads of named block indices. The caller must
    keep registered arrays alive until close()."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native transfer library unavailable")
        self._lib = lib
        self._handle = lib.dtpu_agent_new(host.encode(), port)
        if not self._handle:
            raise RuntimeError(f"failed to bind transfer agent on {host}:{port}")
        self.port = lib.dtpu_agent_port(self._handle)
        self._regions = {}  # region_id -> ndarray (keepalive)

    def register(self, region_id: int, arena: np.ndarray, block_bytes: int) -> None:
        if not arena.flags["C_CONTIGUOUS"]:
            raise ValueError("arena must be C-contiguous")
        if arena.nbytes % block_bytes:
            raise ValueError("arena size must be a multiple of block_bytes")
        rc = self._lib.dtpu_agent_register(
            self._handle, region_id,
            arena.ctypes.data_as(ctypes.c_void_p),
            block_bytes, arena.nbytes // block_bytes,
        )
        if rc != 0:
            raise RuntimeError("region registration failed")
        self._regions[region_id] = arena

    def unregister(self, region_id: int) -> None:
        self._lib.dtpu_agent_unregister(self._handle, region_id)
        self._regions.pop(region_id, None)

    def close(self) -> None:
        if self._handle:
            rc = self._lib.dtpu_agent_free(self._handle)
            self._handle = None
            if rc == 1:
                # teardown leaked the agent: its connection threads may still
                # writev from our arenas, so the buffers must outlive us —
                # park them for the process lifetime instead of freeing
                log.warning(
                    "native agent leaked on close; pinning %d arena(s) for "
                    "process lifetime", len(self._regions),
                )
                _LEAKED_ARENAS.append(dict(self._regions))
            self._regions.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def native_fetch(
    host: str,
    port: int,
    region_id: int,
    block_ids: Sequence[int],
    block_bytes: int,
) -> np.ndarray:
    """Client side: gather remote blocks into one contiguous buffer.
    Returns a uint8 array of shape [n, block_bytes]. Raises on failure.
    Runs on executor threads — the sync fault point is safe here."""
    FAULTS.inject("transfer.native_fetch")
    lib = _load()
    if lib is None:
        raise RuntimeError("native transfer library unavailable")
    n = len(block_ids)
    ids = np.asarray(block_ids, np.uint64)
    out = np.empty((n, block_bytes), np.uint8)
    got = lib.dtpu_fetch(
        host.encode(), port, region_id,
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n,
        out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
    )
    if got != out.nbytes:
        raise RuntimeError(f"native fetch failed: rc={got}, expected {out.nbytes}")
    return out
