"""Mocker: an accelerator-free engine simulating paged-KV continuous batching.

Analog of the reference's mocker (lib/mocker/src/{scheduler,kv_manager,
evictor}.rs, MockEngineArgs at protocols.rs:89-129, behavior documented in
docs/mocker/mocker.md:7-24): simulates block allocation, prefix-cache reuse,
LRU eviction, chunked prefill, watermark admission and step timing — so the
entire control plane (router, planner, frontends, fault tolerance) can be
exercised at fleet scale with zero TPUs.

Deterministic token generation: output token ids derive from a hash of
(request_id, position), so tests can assert exact streams.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, AsyncIterator, Dict, List, Optional, Set

from ..kv_router.publisher import KvEventPublisher, WorkerMetricsPublisher
from ..runtime.engine import Context
from ..runtime.logging import get_logger
from ..runtime.clock import WALL, Clock
from ..tokens import SequenceHash, TokenBlockSequence
from ..llm.protocols.common import (
    FINISH_ERROR,
    FINISH_LENGTH,
    FINISH_STOP,
    BackendOutput,
    PreprocessedRequest,
)

log = get_logger("mocker")


@dataclasses.dataclass
class MockEngineArgs:
    """Mirrors the reference's MockEngineArgs (lib/mocker/src/protocols.rs:89-129)."""

    num_blocks: int = 4096
    block_size: int = 16
    watermark: float = 0.01            # fraction of blocks kept free
    max_num_seqs: int = 256
    max_num_batched_tokens: int = 8192
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    speedup_ratio: float = 1.0         # >1 -> faster simulated clock
    # stamp every emitted token with the engine's simulated clock
    # (annotations["sim_ts"]) so benchmarks measure TTFT/ITL in simulated
    # time, immune to host asyncio jitter amplified by speedup_ratio
    emit_sim_ts: bool = False
    # measured timing grid (.npz from the profiler) replacing the linear
    # constants above — mocker/perf_model.py, reference perf_model.rs
    perf_model_path: Optional[str] = None
    dp_size: int = 1
    startup_time_s: float = 0.0
    # timing model: per-iteration costs (seconds)
    prefill_base_s: float = 0.02
    prefill_per_token_s: float = 0.0001
    decode_base_s: float = 0.005
    decode_per_kv_block_s: float = 0.000002


def _mock_token(request_id: str, position: int, vocab: int = 250) -> int:
    h = hashlib.blake2b(f"{request_id}:{position}".encode(), digest_size=4).digest()
    return 32 + int.from_bytes(h, "little") % vocab  # printable-byte range


class KvBlockState:
    """Paged-KV bookkeeping: active (pinned) + cached (evictable LRU) blocks."""

    def __init__(self, args: MockEngineArgs):
        self.args = args
        self.capacity = args.num_blocks
        # seq_hash -> refcount (active use by running requests)
        self.active: Dict[SequenceHash, int] = {}
        # LRU of inactive cached blocks (prefix cache), most-recent last
        self.cached: OrderedDict[SequenceHash, None] = OrderedDict()
        self.events_stored: List[List[SequenceHash]] = []
        self.events_removed: List[List[SequenceHash]] = []

    # -- accounting ----------------------------------------------------------
    @property
    def used_blocks(self) -> int:
        return len(self.active) + len(self.cached)

    @property
    def free_blocks(self) -> int:
        return self.capacity - len(self.active) - len(self.cached)

    def evictable_blocks(self) -> int:
        return len(self.cached)

    def can_allocate(self, n_new: int) -> bool:
        headroom = self.capacity * (1.0 - self.args.watermark)
        return len(self.active) + n_new <= headroom + 1e-9

    # -- operations ----------------------------------------------------------
    def cached_prefix_len(self, hashes: List[SequenceHash]) -> int:
        """Contiguous leading blocks already present (active or cached)."""
        n = 0
        for h in hashes:
            if h in self.active or h in self.cached:
                n += 1
            else:
                break
        return n

    def acquire(self, hashes: List[SequenceHash]) -> Optional[List[SequenceHash]]:
        """Pin blocks for a running request, reusing cache, evicting LRU as
        needed. Returns newly-stored hashes, or None if out of memory."""
        new: List[SequenceHash] = []
        needed = 0
        for h in hashes:
            if h not in self.active and h not in self.cached:
                needed += 1
        # evict from LRU until there is room (only blocks not being acquired)
        acquiring: Set[SequenceHash] = set(hashes)
        evicted: List[SequenceHash] = []
        while self.free_blocks < needed:
            victim = None
            for h in self.cached:
                if h not in acquiring:
                    victim = h
                    break
            if victim is None:
                return None
            self.cached.pop(victim)
            evicted.append(victim)
        if evicted:
            self.events_removed.append(evicted)
        if not self.can_allocate(sum(1 for h in hashes if h not in self.active)):
            # re-insert nothing; admission simply fails this cycle
            return None
        for h in hashes:
            if h in self.active:
                self.active[h] += 1
            elif h in self.cached:
                self.cached.pop(h)
                self.active[h] = 1
            else:
                self.active[h] = 1
                new.append(h)
        if new:
            self.events_stored.append(new)
        return new

    def release(self, hashes: List[SequenceHash]) -> None:
        """Unpin: blocks move to the prefix cache (LRU) when refcount hits 0."""
        for h in hashes:
            rc = self.active.get(h)
            if rc is None:
                continue
            if rc <= 1:
                del self.active[h]
                if self.args.enable_prefix_caching:
                    self.cached[h] = None
                    self.cached.move_to_end(h)
                else:
                    self.events_removed.append([h])
            else:
                self.active[h] = rc - 1

    def drain_events(self):
        stored, self.events_stored = self.events_stored, []
        removed, self.events_removed = self.events_removed, []
        return stored, removed


@dataclasses.dataclass
class _Running:
    req: PreprocessedRequest
    context: Context
    seq: TokenBlockSequence              # prompt + generated tokens
    out_queue: asyncio.Queue
    prefill_remaining: int               # tokens of prompt not yet prefilled
    cached_tokens: int = 0
    produced: int = 0
    acquired: List[SequenceHash] = dataclasses.field(default_factory=list)
    done: bool = False


class MockerEngine:
    """AsyncEngine with a continuous-batching simulation loop."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        kv_publisher: Optional[KvEventPublisher] = None,
        metrics_publisher: Optional[WorkerMetricsPublisher] = None,
        clock: Optional[Clock] = None,
    ):
        self.args = args or MockEngineArgs()
        from .perf_model import load_perf_model

        self.perf = load_perf_model(self.args.perf_model_path, self.args)
        self.kv = KvBlockState(self.args)
        self.kv_publisher = kv_publisher
        self.metrics_publisher = metrics_publisher
        # pacing clock: WALL for live use; the fleet simulator injects a
        # VirtualClock so step sleeps and startup delays become discrete
        # virtual-time events (sim/clock.py)
        self.clock = clock or WALL
        self._waiting: List[_Running] = []
        self._running: List[_Running] = []
        self._outbox: List = []  # (queue, BackendOutput) deferred past the step sleep
        self.sim_time = 0.0      # simulated seconds of engine compute elapsed
        self._loop_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._started_at = self.clock.time()
        self._stopped = False

    # -- engine interface ----------------------------------------------------
    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req = request if isinstance(request, PreprocessedRequest) else PreprocessedRequest.from_obj(request)
        self._ensure_loop()
        startup_left = self.args.startup_time_s - (self.clock.time() - self._started_at)
        if startup_left > 0:
            await self.clock.sleep(startup_left / self.args.speedup_ratio)
        if self._stopped:
            # stop() ran during the startup sleep: the loop's stranded-
            # consumer flush already happened, so erroring here is the only
            # way this request ever finishes
            yield BackendOutput(finish_reason=FINISH_ERROR, cumulative_tokens=0)
            return
        seq = TokenBlockSequence(req.token_ids, self.args.block_size)
        state = _Running(
            req=req,
            context=context,
            seq=seq,
            out_queue=asyncio.Queue(),
            prefill_remaining=len(req.token_ids),
        )
        self._waiting.append(state)
        self._wake.set()
        while True:
            item = await state.out_queue.get()
            if item is None:
                return
            yield item
            if item.finish_reason is not None:
                return

    # -- simulation loop -----------------------------------------------------
    def _ensure_loop(self) -> None:
        if self._stopped:
            return  # a stopped engine must not resurrect its loop
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        self._stopped = True
        if self._loop_task is not None:
            self._loop_task.cancel()

    async def _loop(self) -> None:
        try:
            while True:
                if not self._waiting and not self._running:
                    self._wake.clear()
                    await self._wake.wait()
                self._admit()
                step_time = await self._step()
                # timing fidelity: a step's tokens become visible only after
                # the simulated step duration has elapsed — a real engine's
                # first token arrives AFTER prefill compute, so TTFT
                # measurements (profiler, benchmarks) see the model's cost
                await self.clock.sleep(step_time / self.args.speedup_ratio)
                self.sim_time += step_time
                if self.args.emit_sim_ts:
                    for _, item in self._outbox:
                        item.annotations["sim_ts"] = self.sim_time
                outbox, self._outbox = self._outbox, []
                for q, item in outbox:
                    q.put_nowait(item)
                await self._publish_events()
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("mocker loop crashed")
        finally:
            # never strand a consumer on a queue whose output was computed
            # but not yet delivered when the loop died
            for q, item in self._outbox:
                q.put_nowait(item)
            self._outbox = []
            # ...nor one whose request was still queued/running: deliver an
            # error finish so generate() returns (the engine-side loop-crash
            # path does the same; without this, stop() mid-request hangs the
            # consumer forever)
            for st in self._waiting + self._running:
                st.out_queue.put_nowait(
                    BackendOutput(
                        finish_reason=FINISH_ERROR, cumulative_tokens=st.produced
                    )
                )
            self._waiting = []
            self._running = []

    def _admit(self) -> None:
        still_waiting: List[_Running] = []
        for st in self._waiting:
            if st.context.is_stopped():
                st.out_queue.put_nowait(
                    BackendOutput(finish_reason="cancelled", cumulative_tokens=0)
                )
                continue
            if len(self._running) >= self.args.max_num_seqs:
                still_waiting.append(st)
                continue
            hashes = st.seq.sequence_hashes()
            cached = (
                self.kv.cached_prefix_len(hashes) if self.args.enable_prefix_caching else 0
            )
            needed_new = sum(
                1 for h in hashes if h not in self.kv.active and h not in self.kv.cached
            )
            if not self.kv.can_allocate(needed_new) and self.kv.evictable_blocks() < needed_new:
                still_waiting.append(st)  # not enough memory yet
                continue
            if self.kv.acquire(hashes) is None:
                still_waiting.append(st)
                continue
            st.acquired = list(hashes)
            st.cached_tokens = cached * self.args.block_size
            st.prefill_remaining = max(0, len(st.req.token_ids) - st.cached_tokens)
            self._running.append(st)
        self._waiting = still_waiting

    async def _step(self) -> float:
        """One engine iteration; returns simulated duration (seconds)."""
        if not self._running:
            return 0.001
        duration = 0.0
        prefill_budget = self.args.max_num_batched_tokens
        decode_kv_blocks = 0
        finished: List[_Running] = []

        for st in self._running:
            if st.context.is_stopped():
                self._outbox.append((
                    st.out_queue,
                    BackendOutput(finish_reason="cancelled", cumulative_tokens=st.produced),
                ))
                finished.append(st)
                continue
            if st.prefill_remaining > 0:
                chunk = (
                    min(st.prefill_remaining, prefill_budget)
                    if self.args.enable_chunked_prefill
                    else st.prefill_remaining
                )
                if chunk <= 0:
                    continue
                st.prefill_remaining -= chunk
                prefill_budget -= chunk
                duration += self.perf.prefill_time(chunk)
                if st.prefill_remaining == 0:
                    # first token arrives with prefill completion
                    self._emit_token(st)
                    if st.done:
                        finished.append(st)
                continue
            # decode: one token per iteration
            decode_kv_blocks += st.seq.num_blocks()
            self._emit_token(st)
            if st.done:
                finished.append(st)

        n_decoding = sum(
            1 for st in self._running
            if st.prefill_remaining == 0 and not st.done
        )
        duration += self.perf.decode_time(n_decoding, decode_kv_blocks)

        for st in finished:
            self._running.remove(st)
            self.kv.release(st.acquired)
        return max(duration, 0.0005)

    def _emit_token(self, st: _Running) -> None:
        first = st.produced == 0  # covers full-cache-hit requests that skip prefill
        tid = _mock_token(st.req.request_id, st.produced)
        st.produced += 1
        sealed = st.seq.append(tid)
        if sealed is not None:
            got = self.kv.acquire([sealed.sequence_hash])
            if got is not None:
                st.acquired.append(sealed.sequence_hash)
        finish: Optional[str] = None
        limit = st.req.stop.max_tokens
        if limit is not None and st.produced >= limit:
            finish = FINISH_LENGTH
        # deterministic "natural" stop: ~1/128 chance per token via hash
        # (suppressed by ignore_eos, like a real engine's EOS handling —
        # benchmark sweeps rely on exact requested lengths)
        elif (
            not st.req.stop.ignore_eos
            and _mock_token(st.req.request_id, st.produced - 1, 1 << 16) % 128 == 0
            and st.produced > st.req.stop.min_tokens
        ):
            finish = FINISH_STOP
        ann = {}
        if first:
            ann = {
                "cached_tokens": st.cached_tokens,
                "input_tokens": len(st.req.token_ids),
            }
        self._outbox.append((
            st.out_queue,
            BackendOutput(
                token_ids=[tid],
                finish_reason=finish,
                cumulative_tokens=st.produced,
                annotations=ann,
            ),
        ))
        if finish is not None:
            st.done = True

    async def _publish_events(self) -> None:
        stored, removed = self.kv.drain_events()
        if self.kv_publisher is not None:
            for batch in stored:
                await self.kv_publisher.stored(batch)
            for batch in removed:
                await self.kv_publisher.removed(batch)
        if self.metrics_publisher is not None:
            bs = self.args.block_size
            await self.metrics_publisher.publish(
                active_decode_blocks=len(self.kv.active),
                num_requests_waiting=len(self._waiting),
                num_requests_active=len(self._running),
                total_blocks=self.args.num_blocks,
                # queued work in block units: without this the report
                # erases the router's optimistic charges for requests
                # that are accepted but not yet admitted, so a backed-up
                # worker scores as if it were serving a single request
                waiting_prefill_blocks=sum(
                    (len(st.req.token_ids) + bs - 1) // bs
                    for st in self._waiting
                ),
            )

    # -- introspection (for planner/tests) ------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "running": len(self._running),
            "waiting": len(self._waiting),
            "active_blocks": len(self.kv.active),
            "cached_blocks": len(self.kv.cached),
            "free_blocks": self.kv.free_blocks,
        }

    async def clear_kv_blocks(self, levels=None) -> Dict[str, Any]:
        """Runtime prefix-cache reset (reference /clear_kv_blocks against any
        worker type — the mocker honors it like the real engine). Active
        (pinned) blocks stay; the evictable cache empties and the router gets
        a wholesale CLEARED for this worker. The mocker only has a g1: a
        levels list that excludes g1 is a no-op, same as the real engine."""
        if levels is not None and (
            not isinstance(levels, (list, tuple))
            or any(not isinstance(lv, str) for lv in levels)
        ):
            raise ValueError("levels must be a list of tier names")
        result: Dict[str, Any] = {}
        if levels is None or "g1" in [lv.lower() for lv in levels]:
            result["g1"] = len(self.kv.cached)
            self.kv.cached.clear()
            if self.kv_publisher is not None:
                await self.kv_publisher.cleared()
        result["snapshot"] = self.snapshot()
        return result
