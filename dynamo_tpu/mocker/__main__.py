"""python -m dynamo_tpu.mocker — accelerator-free worker for fleet testing.

Analog of the reference's `python -m dynamo.mocker`
(components/src/dynamo/mocker): registers a MockerEngine as a real worker —
request plane endpoint, model card, KV events, load metrics — so routers,
planners and frontends can be exercised at scale on one box.
"""

import argparse
import asyncio
import signal

from dynamo_tpu.kv_router import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm import ModelDeploymentCard, ModelRuntimeConfig, register_llm
from dynamo_tpu.llm.serve import serve_clear_endpoint
from dynamo_tpu.mocker.engine import MockEngineArgs, MockerEngine
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging
from dynamo_tpu.runtime.component import new_instance_id


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.mocker")
    p.add_argument("--model", default="mock-model", help="served model name")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--num-blocks", type=int, default=4096)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-num-seqs", type=int, default=256)
    p.add_argument("--speedup-ratio", type=float, default=1.0)
    p.add_argument("--startup-time", type=float, default=0.0)
    p.add_argument("--migration-limit", type=int, default=0)
    p.add_argument("--model-type", default="chat,completions")
    p.add_argument("--num-workers", type=int, default=1, help="instances in this process")
    p.add_argument("--status-port", type=int, default=-1,
                   help="system status server port (0 = ephemeral, -1 = off)")
    p.add_argument("--profile", default=None,
                   help="profile JSON (python -m dynamo_tpu.profiler): "
                   "calibrates the simulated timing to the measured engine "
                   "(perf_model.rs analog)")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()

    base_args = MockEngineArgs(
        num_blocks=args.num_blocks,
        block_size=args.block_size,
        max_num_seqs=args.max_num_seqs,
        speedup_ratio=args.speedup_ratio,
        startup_time_s=args.startup_time,
    )
    if args.profile:
        from dynamo_tpu.profiler import ProfileResult, calibrate_mocker_args

        base_args = calibrate_mocker_args(ProfileResult.load(args.profile), base_args)
        print(
            f"MOCKER_CALIBRATED prefill={base_args.prefill_base_s:.4f}"
            f"+{base_args.prefill_per_token_s * 1e6:.1f}us/tok "
            f"decode={base_args.decode_base_s * 1e3:.2f}ms"
            f"+{base_args.decode_per_kv_block_s * 1e6:.3f}us/blk",
            flush=True,
        )

    served = []
    aux_served = []
    for _ in range(args.num_workers):
        instance_id = new_instance_id()
        engine_args = base_args
        kv_pub = KvEventPublisher(
            runtime.event_plane, args.namespace, args.component,
            worker_id=instance_id, block_size=args.block_size,
        )
        m_pub = WorkerMetricsPublisher(
            runtime.event_plane, args.namespace, args.component, worker_id=instance_id
        )
        engine = MockerEngine(engine_args, kv_pub, m_pub)
        card = ModelDeploymentCard(
            name=args.model,
            namespace=args.namespace,
            component=args.component,
            endpoint=args.endpoint,
            model_type=args.model_type.split(","),
            tokenizer="byte",
            kv_block_size=args.block_size,
            migration_limit=args.migration_limit,
            runtime_config=ModelRuntimeConfig(
                total_kv_blocks=args.num_blocks, kv_block_size=args.block_size,
                max_batch_size=args.max_num_seqs,
            ),
        )
        s = await register_llm(runtime, engine, card, instance_id=instance_id)
        served.append(s)
        # cache reset beside generate (reference /clear_kv_blocks works
        # against every worker type)
        aux_served.append(await serve_clear_endpoint(
            runtime, args.namespace, args.component, [engine], instance_id
        ))
    canary = status_server = None
    if args.status_port >= 0:
        from dynamo_tpu.runtime.health import EndpointCanary, HealthState, StatusServer

        health = HealthState()
        canary = EndpointCanary(
            {f"worker/{s.instance_id:016x}": s.address for s in served}, state=health
        ).start()
        status_server = StatusServer(
            health,
            metrics_scope=runtime.metrics,
            metadata_fn=lambda: {"model": args.model, "workers": len(served)},
            port=args.status_port,
        )
        await status_server.start()
    print(f"MOCKER_READY {len(served)} workers", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if canary is not None:
        await canary.stop()
    if status_server is not None:
        await status_server.stop()
    for s in served:
        await s.stop()
    for s in aux_served:
        await s.stop()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
