"""Mocker timing models: polynomial (linear) and NPZ-grid interpolation.

Analog of the reference's mocker perf model (lib/mocker/src/perf_model.rs):
two interchangeable timing sources for the simulated engine clock —

1. ``PolynomialPerfModel``: the MockEngineArgs linear constants (default,
   what ``profiler.sweep.calibrate_mocker_args`` fits from measurements);
2. ``InterpolatedPerfModel``: grids measured by the profiler, loaded from an
   ``.npz`` — 1-D linear interpolation over ISL for prefill, bilinear over
   (active_seqs, kv_blocks) for decode — so the simulator reproduces a real
   engine's measured timing surface, not a fitted line.

NPZ schema (all float64):
    prefill_isl [N], prefill_s [N]                 # chunk latency by length
    decode_seqs [A], decode_blocks [B], decode_s [A, B]   # step latency grid
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class PolynomialPerfModel:
    """Linear-coefficient model (perf_model.rs "Polynomial" analog)."""

    def __init__(self, prefill_base_s: float, prefill_per_token_s: float,
                 decode_base_s: float, decode_per_kv_block_s: float):
        self.prefill_base_s = prefill_base_s
        self.prefill_per_token_s = prefill_per_token_s
        self.decode_base_s = decode_base_s
        self.decode_per_kv_block_s = decode_per_kv_block_s

    @classmethod
    def from_args(cls, args) -> "PolynomialPerfModel":
        return cls(args.prefill_base_s, args.prefill_per_token_s,
                   args.decode_base_s, args.decode_per_kv_block_s)

    def prefill_time(self, chunk_tokens: int) -> float:
        return self.prefill_base_s + self.prefill_per_token_s * chunk_tokens

    def decode_time(self, active_seqs: int, kv_blocks: int) -> float:
        # matches the historical step formula exactly: the base is charged
        # per iteration (covers dispatch overhead even in prefill-only steps)
        return self.decode_base_s + self.decode_per_kv_block_s * kv_blocks


class InterpolatedPerfModel:
    """Measured-grid model (perf_model.rs "Interpolated" analog)."""

    def __init__(self, prefill_isl: np.ndarray, prefill_s: np.ndarray,
                 decode_seqs: np.ndarray, decode_blocks: np.ndarray,
                 decode_s: np.ndarray):
        order = np.argsort(prefill_isl)
        self.prefill_isl = np.asarray(prefill_isl, np.float64)[order]
        self.prefill_s = np.asarray(prefill_s, np.float64)[order]
        self.decode_seqs = np.asarray(decode_seqs, np.float64)
        self.decode_blocks = np.asarray(decode_blocks, np.float64)
        self.decode_s = np.asarray(decode_s, np.float64)
        if self.decode_s.shape != (len(self.decode_seqs), len(self.decode_blocks)):
            raise ValueError(
                f"decode grid {self.decode_s.shape} != "
                f"({len(self.decode_seqs)}, {len(self.decode_blocks)})"
            )

    # -- io -------------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "InterpolatedPerfModel":
        with np.load(path, allow_pickle=False) as z:
            return cls(z["prefill_isl"], z["prefill_s"],
                       z["decode_seqs"], z["decode_blocks"], z["decode_s"])

    def save(self, path: str) -> None:
        np.savez(path, prefill_isl=self.prefill_isl, prefill_s=self.prefill_s,
                 decode_seqs=self.decode_seqs, decode_blocks=self.decode_blocks,
                 decode_s=self.decode_s)

    # -- queries --------------------------------------------------------------
    def prefill_time(self, chunk_tokens: int) -> float:
        # clamped linear interpolation (np.interp clamps at the edges)
        return float(np.interp(chunk_tokens, self.prefill_isl, self.prefill_s))

    def decode_time(self, active_seqs: int, kv_blocks: int) -> float:
        if active_seqs <= 0:
            return 0.0
        a = float(np.clip(active_seqs, self.decode_seqs[0], self.decode_seqs[-1]))
        b = float(np.clip(kv_blocks, self.decode_blocks[0], self.decode_blocks[-1]))
        # bilinear over the (seqs, blocks) grid
        i = int(np.searchsorted(self.decode_seqs, a, side="right") - 1)
        j = int(np.searchsorted(self.decode_blocks, b, side="right") - 1)
        i = min(i, len(self.decode_seqs) - 2) if len(self.decode_seqs) > 1 else 0
        j = min(j, len(self.decode_blocks) - 2) if len(self.decode_blocks) > 1 else 0
        if len(self.decode_seqs) == 1 and len(self.decode_blocks) == 1:
            return float(self.decode_s[0, 0])
        if len(self.decode_seqs) == 1:
            return float(np.interp(b, self.decode_blocks, self.decode_s[0]))
        if len(self.decode_blocks) == 1:
            return float(np.interp(a, self.decode_seqs, self.decode_s[:, 0]))
        a0, a1 = self.decode_seqs[i], self.decode_seqs[i + 1]
        b0, b1 = self.decode_blocks[j], self.decode_blocks[j + 1]
        ta = (a - a0) / (a1 - a0) if a1 > a0 else 0.0
        tb = (b - b0) / (b1 - b0) if b1 > b0 else 0.0
        z = self.decode_s
        return float(
            z[i, j] * (1 - ta) * (1 - tb)
            + z[i + 1, j] * ta * (1 - tb)
            + z[i, j + 1] * (1 - ta) * tb
            + z[i + 1, j + 1] * ta * tb
        )


def load_perf_model(path: Optional[str], args) -> object:
    """NPZ path -> InterpolatedPerfModel; None -> the args' linear model."""
    if path:
        return InterpolatedPerfModel.load(path)
    return PolynomialPerfModel.from_args(args)
