"""Pallas TPU kernels: batched KV block gather / scatter / copy.

TPU-native analog of the reference's CUDA batched block-copy kernel
(lib/llm/src/kernels/block_copy.cu, ``copy_blocks_kernel`` :41), which moves
paged-KV blocks between layouts for KVBM offload/onboard. Here the moves are
expressed as explicit HBM<->HBM DMAs driven by scalar-prefetched index lists —
no VMEM round-trip, no materialized gather indices, and the batch of copies
runs as overlapping async DMAs.

Used by:
  - engine/transfer.py: gather sealed blocks into a contiguous staging buffer
    for the transfer plane (disaggregation KV handoff);
  - kvbm: onboarding host/disk blocks back into device pages;
  - allocator defragmentation (copy_blocks).

All entry points fall back to pure-JAX gather/scatter off-TPU (CPU tests, and
interpret=True runs the real kernel in the Pallas interpreter).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(ids_ref, cache_hbm, out_hbm, sem):
    """grid=(M,): DMA cache[ids[m]] -> out[m], HBM->HBM."""
    m = pl.program_id(0)
    dma = pltpu.make_async_copy(
        cache_hbm.at[ids_ref[m]], out_hbm.at[m], sem
    )
    dma.start()
    dma.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_blocks(
    cache: jax.Array,      # [num_blocks, bs, kvh, d] (or [num_blocks, ...])
    block_ids: jax.Array,  # [M] int32
    *,
    interpret: bool = False,
) -> jax.Array:
    """Gather pages ``cache[block_ids]`` into a contiguous [M, ...] buffer."""
    M = block_ids.shape[0]
    out_shape = (M,) + cache.shape[1:]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(M,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(out_shape, cache.dtype),
        interpret=interpret,
    )(block_ids.astype(jnp.int32), cache)


def _scatter_kernel(ids_ref, blocks_hbm, cache_io, sem):
    """grid=(M,): DMA blocks[m] -> cache[ids[m]] in place (aliased)."""
    m = pl.program_id(0)
    dma = pltpu.make_async_copy(
        blocks_hbm.at[m], cache_io.at[ids_ref[m]], sem
    )
    dma.start()
    dma.wait()


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def scatter_blocks(
    cache: jax.Array,      # [num_blocks, ...] donated, updated in place
    block_ids: jax.Array,  # [M] int32 destination pages
    blocks: jax.Array,     # [M, ...] source pages
    *,
    interpret: bool = False,
) -> jax.Array:
    """Scatter contiguous pages into ``cache[block_ids]``; returns the updated
    cache (same buffer — input is donated/aliased)."""
    M = block_ids.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(M,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # blocks
            pl.BlockSpec(memory_space=pl.ANY),  # cache (aliased to out)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )

    def kernel(ids_ref, blocks_hbm, cache_in, cache_io, sem):
        del cache_in  # aliased with cache_io
        _scatter_kernel(ids_ref, blocks_hbm, cache_io, sem)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        input_output_aliases={2: 0},  # cache (after 1 scalar-prefetch arg + blocks)
        interpret=interpret,
    )(block_ids.astype(jnp.int32), blocks, cache)


def _copy_kernel(src_ref, dst_ref, cache_in, cache_io, sem):
    """grid=(M,): DMA cache[src[m]] -> cache[dst[m]] in place."""
    del cache_in
    m = pl.program_id(0)
    dma = pltpu.make_async_copy(
        cache_io.at[src_ref[m]], cache_io.at[dst_ref[m]], sem
    )
    dma.start()
    dma.wait()


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def copy_blocks(
    cache: jax.Array,    # [num_blocks, ...] donated
    src_ids: jax.Array,  # [M] int32
    dst_ids: jax.Array,  # [M] int32 (disjoint from src_ids)
    *,
    interpret: bool = False,
) -> jax.Array:
    """Batched page copy within one cache (defrag / prefix fork)."""
    M = src_ids.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(M,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(cache.shape, cache.dtype),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(src_ids.astype(jnp.int32), dst_ids.astype(jnp.int32), cache)


# -- pure-JAX fallbacks (CPU / non-TPU backends) -----------------------------
def gather_blocks_ref(cache: jax.Array, block_ids: jax.Array) -> jax.Array:
    return cache[block_ids]


def scatter_blocks_ref(
    cache: jax.Array, block_ids: jax.Array, blocks: jax.Array
) -> jax.Array:
    return cache.at[block_ids].set(blocks)


def copy_blocks_ref(
    cache: jax.Array, src_ids: jax.Array, dst_ids: jax.Array
) -> jax.Array:
    return cache.at[dst_ids].set(cache[src_ids])


# -- quantized caches (ops/quant.QuantizedKV) --------------------------------
# A quantized page move is two moves — the int8 payload and its f32 scale
# row — that MUST travel together (a payload under the wrong scale is silent
# corruption, not an error). These wrappers keep the pair atomic for the
# KVBM offload/onboard and transfer staging paths; per-array they reuse the
# same DMA kernels/refs above, so the TPU path stays all-async.
# NOTE (hardware): the scale array's DMA slice is a [kvh] f32 row (minor dim
# not 128-aligned) — the SAME Mosaic caveat flagged on the in-kernel scale
# DMA in pallas_attention._decode_kernel; the first real-TPU int8 run must
# confirm both sites (fallback: the _ref paths below, or kv_dtype=model).
def gather_blocks_quant(cache, block_ids: jax.Array, *, interpret: bool = False):
    """QuantizedKV pages -> (payload [M, bs, kvh, d] int8, scales [M, kvh])."""
    from .quant import QuantizedKV

    if on_tpu() or interpret:
        return QuantizedKV(
            gather_blocks(cache.data, block_ids, interpret=interpret),
            gather_blocks(cache.scale, block_ids, interpret=interpret),
        )
    return QuantizedKV(
        gather_blocks_ref(cache.data, block_ids),
        gather_blocks_ref(cache.scale, block_ids),
    )


def scatter_blocks_quant(
    cache, block_ids: jax.Array, blocks, *, interpret: bool = False
):
    """Scatter (payload, scales) pages into a QuantizedKV cache."""
    from .quant import QuantizedKV

    if on_tpu() or interpret:
        return QuantizedKV(
            scatter_blocks(cache.data, block_ids, blocks.data,
                           interpret=interpret),
            scatter_blocks(cache.scale, block_ids, blocks.scale,
                           interpret=interpret),
        )
    return QuantizedKV(
        scatter_blocks_ref(cache.data, block_ids, blocks.data),
        scatter_blocks_ref(cache.scale, block_ids, blocks.scale),
    )


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"
