"""Pallas TPU kernel: unified ragged paged attention (prefill + decode fused).

ONE launch serves an arbitrary mix of prefill chunks and decode tokens — the
"Ragged Paged Attention" formulation (PAPERS.md) that lets the engine step
loop run true continuous batches instead of alternating a prefill-only
kernel (ops/pallas_prefill.py flash extend) with a decode-only kernel
(ops/pallas_attention.py ragged decode). Rows carry ``(query_len, seq_len)``
pairs: query tokens pack densely into one ragged buffer, each row's segment
sits at the TAIL of its own paged context, and causal masking is per row.

Beyond the base pair, rows may carry OPTIONAL per-row attributes — the
additions that let the gated model families ride the same launch:

- ``windows`` [R] int32: per-row sliding-window bound (``<= 0`` = full
  attention). Key ``j`` is visible to query ``i`` iff ``i - w < j <= i``,
  and the page-chunk loop STARTS at the first chunk the row's earliest
  query can see — a 128-token window over a 128k context streams ~window
  keys, not the whole cache (the gpt-oss/gemma sliding layers);
- ``sinks`` [h] f32: per-head attention-sink logits (gpt-oss), folded into
  the softmax denominator by seeding each tile's online-softmax state with
  the sink as a virtual zero-value key (``m0 = sink, l0 = 1, acc0 = 0``) —
  algebraically identical to ops/attention._sink_softmax;
- ``softcap`` (static float): gemma-2 logit softcapping,
  ``cap * tanh(s / cap)`` applied post-scale, pre-mask.

A speculative-decode verify pass is just a row with ``query_len = k + 1``
(candidate tokens at the context tail) — no special case in the kernel.

Versus the two split kernels this also removes two whole classes of HBM
traffic:

- no gather: the prefill side of the split path materializes the FULL
  padded context (``gather_kv`` over ``max_blocks_per_seq`` pages, an
  HBM->HBM copy) before the flash kernel even starts; here KV pages stream
  straight from the paged cache, and only the ``ceil(seq_len / bs)`` real
  pages of each row are ever touched;
- single pass over KV: the flash-extend grid re-reads the gathered context
  once per q tile; here the chunk loop is OUTER and the q-tile loop INNER,
  so each row's pages are DMA'd exactly once per kv head regardless of how
  many query tokens ride on them.

``ops/costs.py`` turns both layouts into byte counts; the tier-1 gate pins
mixed <= split (including the windowed and spec-verify row shapes).

Layout/machinery shared with the PR 2 kernels: paged cache
``[num_blocks, block_size, kv_heads, head_dim]``; int8 caches
(ops/quant.QuantizedKV) DMA the int8 pages PLUS their per-block
``[kvh]`` f32 scale rows on the same scalar-prefetched table indices and
dequantize in-register (the scale-row DMA machinery introduced by the
decode kernel — and carrying the same hardware caveat: the scale row's
minor dim is kvh, not 128-aligned; CPU tier-1 exercises interpret mode
only, and tests/test_unified_attention.py pins the grow-scale rescale RMW
path there).

Grid: ``(kvh, R)`` — kv head OUTER so the packed q/o blocks for one head
stay VMEM-resident across all R rows; rows iterate on the minor dim. Per
(head, row): double-buffered page-slice DMAs (``[bs, d]`` per page for this
head) chunked ``chunk_pages`` at a time, a DYNAMIC inner loop over the
row's ``ceil(q_len / q_seg)`` query tiles with online-softmax state per
tile in VMEM scratch, and a masked read-modify-write emit so neighbouring
segments' outputs survive clamped tile writes. A decode row costs one
``q_seg``-row tile per chunk (bandwidth-bound, unchanged page bytes); a
prefill chunk amortizes the same page stream over all its tiles.

NOTE (hardware): the dynamic scratch slices step in ``q_seg * g`` sublanes
and the per-head page DMA strides over kv heads; both run interpret-clean
and need the first real-TPU run to confirm Mosaic lowering (same protocol
as the PR 2 scale-row caveat — fallback: use_pallas=False). The windowed
variant additionally starts its chunk loop at a traced lower bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import shard_map
from .quant import QuantizedKV, is_quantized

NEG_INF = -1e30

# default query-tile rows per inner iteration: small enough that a decode
# row (q_len=1) stays bandwidth-bound, large enough that q_seg * g fills
# MXU sublanes for common GQA group sizes
Q_SEG = 8


def _unified_kernel(
    *args,
    max_blocks: int,
    chunk_pages: int,
    q_seg: int,
    quantized: bool,
    has_window: bool,
    has_sinks: bool,
    softcap,
):
    # args layout (optional pieces gated by the static flags):
    #   scalar prefetch (SMEM): starts [R], qlens [R], lens [R],
    #     [windows [R]], tables [R * max_blocks]
    #   inputs: q VMEM [1, Tq, g, d], [sinks VMEM [1, g]],
    #     k/v ANY/HBM [num_blocks, bs, kvh, d],
    #     [k/v scales ANY/HBM [num_blocks, kvh] f32]
    #   outputs: o VMEM [1, Tq, g, d]
    #   scratch: k/v_buf VMEM [2, CP, bs, d], [k/v scale bufs [2, CP, kvh]],
    #     m/l/acc VMEM [Tq_pad*g, 1/1/d] f32, DMA sems [2, 2, CP] (+quant)
    it = iter(args)
    starts_ref = next(it)
    qlens_ref = next(it)
    lens_ref = next(it)
    windows_ref = next(it) if has_window else None
    tables_ref = next(it)
    q_ref = next(it)
    sinks_ref = next(it) if has_sinks else None
    k_hbm = next(it)
    v_hbm = next(it)
    ks_hbm = vs_hbm = None
    if quantized:
        ks_hbm = next(it)
        vs_hbm = next(it)
    o_ref = next(it)
    k_buf = next(it)
    v_buf = next(it)
    ks_buf = vs_buf = None
    if quantized:
        ks_buf = next(it)
        vs_buf = next(it)
    m_scr = next(it)
    l_scr = next(it)
    acc_scr = next(it)
    sem = next(it)
    ssem = next(it) if quantized else None

    kh = pl.program_id(0)
    r = pl.program_id(1)
    bs, kvh, d = k_hbm.shape[1], k_hbm.shape[2], k_hbm.shape[3]
    Tq, g = q_ref.shape[1], q_ref.shape[2]
    CP = chunk_pages
    T = CP * bs
    QG = q_seg * g

    q_start = starts_ref[r]
    q_len = qlens_ref[r]
    seq_len = lens_ref[r]
    w = windows_ref[r] if has_window else None

    @pl.when(r == 0)
    def _zero_out():
        # fresh block per kv head: padding tokens (gaps between segments)
        # must read back deterministic zeros, matching the reference twin
        o_ref[...] = jnp.zeros_like(o_ref)

    num_pages = pl.cdiv(seq_len, bs)
    num_chunks = pl.cdiv(num_pages, CP)
    nq = pl.cdiv(q_len, q_seg)
    active = jnp.logical_and(q_len > 0, seq_len > 0)
    chunks = jnp.where(active, num_chunks, 0)
    ctx_start = seq_len - q_len  # absolute position of the segment's row 0
    if has_window:
        # a windowed row's earliest query (position ctx_start) sees no key
        # below ctx_start - w + 1: pages a sliding window already aged out
        # are never DMA'd (page-granular, like the split decode path's
        # trailing-window gather), and the chunk loop starts at the first
        # chunk holding a live page
        lo_page = jnp.where(
            w > 0, jnp.maximum(ctx_start - w + 1, 0) // bs, 0
        )
        c_lo = lo_page // CP
    else:
        lo_page = 0
        c_lo = 0

    def page_dma(kind, c, j, slot):
        """DMA this kv head's slice of page j of chunk c: [bs, d]."""
        idx = tables_ref[r * max_blocks + c * CP + j]
        src = k_hbm if kind == 0 else v_hbm
        dst = k_buf if kind == 0 else v_buf
        return pltpu.make_async_copy(
            src.at[idx, :, kh], dst.at[slot, j], sem.at[kind, slot, j]
        )

    def scale_dma(kind, c, j, slot):
        """Full [kvh] scale row for page j — the PR 2 scale-row machinery
        (one tiny f32 row riding the same prefetched table index)."""
        idx = tables_ref[r * max_blocks + c * CP + j]
        src = ks_hbm if kind == 0 else vs_hbm
        dst = ks_buf if kind == 0 else vs_buf
        return pltpu.make_async_copy(
            src.at[idx], dst.at[slot, j], ssem.at[kind, slot, j]
        )

    def page_live(c, j):
        """Page j of chunk c holds keys some query of this row can see."""
        live = c * CP + j < num_pages
        if has_window:
            live = jnp.logical_and(live, c * CP + j >= lo_page)
        return live

    def start_chunk(c, slot):
        for j in range(CP):  # static unroll; guard ragged tail + window
            @pl.when(page_live(c, j))
            def _():
                page_dma(0, c, j, slot).start()
                page_dma(1, c, j, slot).start()
                if quantized:
                    scale_dma(0, c, j, slot).start()
                    scale_dma(1, c, j, slot).start()

    def wait_chunk(c, slot):
        for j in range(CP):
            @pl.when(page_live(c, j))
            def _():
                page_dma(0, c, j, slot).wait()
                page_dma(1, c, j, slot).wait()
                if quantized:
                    scale_dma(0, c, j, slot).wait()
                    scale_dma(1, c, j, slot).wait()

    # per-row online-softmax state: one (m, l, acc) strip per q tile,
    # reset every row (only the first nq tiles are ever touched). With
    # sinks, the state is seeded as if one virtual zero-value key with
    # logit sinks[h] had already been folded in (m0 = sink, l0 = 1) —
    # exactly _sink_softmax's denominator term.
    if has_sinks:
        srow = sinks_ref[0].astype(jnp.float32)              # [g], this head
        m_scr[...] = jnp.broadcast_to(
            srow[None, :], (Tq, g)
        ).reshape(Tq * g, 1)
        l_scr[...] = jnp.ones_like(l_scr)
    else:
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(active)
    def _prime():
        start_chunk(c_lo, jax.lax.rem(c_lo, 2) if has_window else 0)

    scale = 1.0 / (d ** 0.5)

    def tile_start(qt):
        # clamped so the static-size q slice stays in bounds; overlapping
        # tiles recompute identical rows (each tile's masks derive from its
        # ACTUAL packed offset, not qt * q_seg)
        return jnp.minimum(q_start + qt * q_seg, Tq - q_seg)

    def chunk_body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < chunks)
        def _():
            start_chunk(c + 1, jax.lax.rem(c + 1, 2))

        wait_chunk(c, slot)

        if quantized:
            # dequantize in-register: this head's scale is one lane of the
            # [CP, kvh] rows that just DMA'd in (lane-select via one-hot —
            # kh is a grid index, so a dynamic lane slice is avoided)
            sel = (
                jax.lax.broadcasted_iota(jnp.int32, (1, kvh), 1) == kh
            ).astype(jnp.float32)                                  # [1, kvh]
            ksc = jnp.sum(ks_buf[slot] * sel, axis=1)              # [CP]
            vsc = jnp.sum(vs_buf[slot] * sel, axis=1)
            k = k_buf[slot].astype(jnp.float32) * ksc[:, None, None]
            v = v_buf[slot].astype(jnp.float32) * vsc[:, None, None]
        else:
            k = k_buf[slot].astype(jnp.float32)
            v = v_buf[slot].astype(jnp.float32)
        k = k.reshape(T, d)
        v = v.reshape(T, d)
        # rows past seq_len were never DMA'd (garbage / NaN): scores are
        # masked below, but V must be zeroed too — 0-weight * NaN = NaN.
        # Same for pages a row's sliding window skipped at the head.
        row_pos = c * T + jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)
        v_live = row_pos < seq_len
        if has_window:
            v_live = jnp.logical_and(v_live, row_pos >= lo_page * bs)
        v = jnp.where(v_live, v, 0.0)
        key_pos = c * T + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)

        def tile_body(qt, carry2):
            seg = tile_start(qt)
            # row index per flattened (q, g) pair in the [QG, 1] layout
            # (iota // g keeps the lane dim fixed — see pallas_prefill)
            row = jax.lax.broadcasted_iota(jnp.int32, (QG, 1), 0) // g
            local = (seg - q_start) + row
            member = jnp.logical_and(local >= 0, local < q_len)
            q_pos = ctx_start + local
            lim = jnp.where(member, jnp.minimum(q_pos + 1, seq_len), 0)
            # causal tile-skip: this chunk's keys start at c*T; the tile's
            # highest attention limit is its last member row's
            hi = jnp.minimum(ctx_start + (seg - q_start) + q_seg, seq_len)
            do_tile = c * T < hi
            if has_window:
                # window tile-skip: the tile's EARLIEST member query sits
                # at q_pos_min; a chunk whose last key is below its window
                # contributes nothing to any row of the tile
                q_pos_min = ctx_start + jnp.maximum(seg - q_start, 0)
                do_tile = jnp.logical_and(
                    do_tile,
                    jnp.where(w > 0, (c + 1) * T > q_pos_min - w + 1, True),
                )

            @pl.when(do_tile)
            def _():
                qf = (
                    q_ref[0, pl.ds(seg, q_seg)].astype(jnp.float32) * scale
                ).reshape(QG, d)
                s = jax.lax.dot_general(
                    qf, k,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )                                                  # [QG, T]
                if softcap is not None:
                    s = jnp.tanh(s / softcap) * softcap
                valid = key_pos < lim
                if has_window:
                    lo = jnp.where(
                        jnp.logical_and(member, w > 0), q_pos - w + 1, 0
                    )
                    valid = jnp.logical_and(valid, key_pos >= lo)
                s = jnp.where(valid, s, NEG_INF)
                sl = pl.ds(qt * QG, QG)
                m_prev = m_scr[sl]
                l_prev = l_scr[sl]
                acc_prev = acc_scr[sl]
                m_cur = jnp.max(s, axis=-1, keepdims=True)
                m_new = jnp.maximum(m_prev, m_cur)
                if has_window:
                    # a windowed row's FIRST visible chunk can still hand a
                    # tile an all-masked score row (the row's own window
                    # starts mid-chunk): exp(NEG_INF - NEG_INF) would be 1,
                    # so masked lanes are zeroed explicitly
                    p = jnp.where(
                        s > NEG_INF * 0.5, jnp.exp(s - m_new), 0.0
                    )
                else:
                    p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                m_scr[sl] = m_new
                l_scr[sl] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
                acc_scr[sl] = alpha * acc_prev + jax.lax.dot_general(
                    p, v,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            return carry2

        jax.lax.fori_loop(0, nq, tile_body, 0)
        return carry

    jax.lax.fori_loop(c_lo, chunks, chunk_body, 0)

    def emit_tile(qt, carry):
        seg = tile_start(qt)
        sl = pl.ds(qt * QG, QG)
        out = acc_scr[sl] / jnp.maximum(l_scr[sl], 1e-30)          # [QG, d]
        row = jax.lax.broadcasted_iota(jnp.int32, (QG, 1), 0) // g
        local = (seg - q_start) + row
        member = jnp.logical_and(local >= 0, local < q_len)
        # masked read-modify-write: a clamped tile spans neighbouring
        # segments' tokens — their already-written outputs must survive
        cur = o_ref[0, pl.ds(seg, q_seg)].astype(jnp.float32).reshape(QG, d)
        merged = jnp.where(member, out, cur)
        o_ref[0, pl.ds(seg, q_seg)] = merged.reshape(
            q_seg, g, d
        ).astype(o_ref.dtype)
        return carry

    @pl.when(active)
    def _emit():
        jax.lax.fori_loop(0, nq, emit_tile, 0)


@functools.partial(
    jax.jit,
    static_argnames=("q_seg", "chunk_tokens", "interpret", "softcap"),
)
def ragged_paged_attention(
    q: jax.Array,             # [Tq, h, d] densely packed ragged queries
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d] (or QuantizedKV)
    v_cache: jax.Array,
    block_tables: jax.Array,  # [R, max_blocks] int32
    q_starts: jax.Array,      # [R] int32
    q_lens: jax.Array,        # [R] int32 (0 = empty row)
    seq_lens: jax.Array,      # [R] int32
    *,
    windows: jax.Array = None,   # [R] int32 per-row window (<=0 = full)
    sinks: jax.Array = None,     # [h] f32 per-head sink logits
    softcap: float = None,       # static logit softcap (gemma-2)
    q_seg: int = Q_SEG,
    chunk_tokens: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Unified ragged paged attention (Pallas). Same semantics as
    ``ops.attention.ragged_paged_attention`` (the pure-JAX reference twin):
    row r's segment ``q[q_starts[r] : q_starts[r]+q_lens[r]]`` attends
    causally over that row's pages with the segment at the context tail;
    tokens outside every segment return zeros. Optional per-row
    ``windows`` (sliding-window bounds), per-head ``sinks`` logits, and a
    static ``softcap`` extend the same launch to the gpt-oss/gemma
    families and spec-verify rows (``q_len = k+1``). ``k_cache``/
    ``v_cache`` may be ``QuantizedKV`` — int8 pages + per-block scale rows
    DMA together and dequantize in-register, halving per-page HBM bytes
    vs bf16."""
    Tq, h, d = q.shape
    _, bs, kvh, _ = k_cache.shape
    R, max_blocks = block_tables.shape
    g = h // kvh
    chunk_pages = max(1, chunk_tokens // bs)
    quantized = is_quantized(k_cache)
    has_window = windows is not None
    has_sinks = sinks is not None

    # pad the packed buffer so every clamped q tile is in bounds
    Tq_pad = max(q_seg, -(-Tq // q_seg) * q_seg)
    if Tq_pad != Tq:
        q = jnp.pad(q, ((0, Tq_pad - Tq), (0, 0), (0, 0)))

    kernel = functools.partial(
        _unified_kernel, max_blocks=max_blocks, chunk_pages=chunk_pages,
        q_seg=q_seg, quantized=quantized, has_window=has_window,
        has_sinks=has_sinks, softcap=softcap,
    )
    cache_specs = [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch = [
        pltpu.VMEM((2, chunk_pages, bs, d), k_cache.dtype),
        pltpu.VMEM((2, chunk_pages, bs, d), v_cache.dtype),
    ]
    if quantized:
        cache_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # k scales [num_blocks, kvh]
            pl.BlockSpec(memory_space=pl.ANY),  # v scales
        ]
        scratch += [
            pltpu.VMEM((2, chunk_pages, kvh), jnp.float32),
            pltpu.VMEM((2, chunk_pages, kvh), jnp.float32),
        ]
    scratch += [
        pltpu.VMEM((Tq_pad * g, 1), jnp.float32),   # m
        pltpu.VMEM((Tq_pad * g, 1), jnp.float32),   # l
        pltpu.VMEM((Tq_pad * g, d), jnp.float32),   # acc
    ]
    scratch.append(pltpu.SemaphoreType.DMA((2, 2, chunk_pages)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2, chunk_pages)))

    # [Tq, h, d] -> [kvh, Tq, g, d]: each kv head's q group contiguous; the
    # kv head is the OUTER grid dim so the block stays resident across rows
    qg = q.reshape(Tq_pad, kvh, g, d).transpose(1, 0, 2, 3)
    in_specs = [
        pl.BlockSpec((1, Tq_pad, g, d), lambda kh, r, *_: (kh, 0, 0, 0))
    ]
    if has_sinks:
        # this head's [g] sink logits ride a tiny VMEM block; the head
        # grouping matches the q reshape (head = kh * g + gi)
        in_specs.append(
            pl.BlockSpec((1, g), lambda kh, r, *_: (kh, 0))
        )
    in_specs += cache_specs
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 + (1 if has_window else 0),
        grid=(kvh, R),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, Tq_pad, g, d), lambda kh, r, *_: (kh, 0, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    cache_args = (
        (k_cache.data, v_cache.data, k_cache.scale, v_cache.scale)
        if quantized else (k_cache, v_cache)
    )
    prefetch = [
        q_starts.astype(jnp.int32),
        q_lens.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
    ]
    if has_window:
        prefetch.append(windows.astype(jnp.int32))
    prefetch.append(block_tables.reshape(-1).astype(jnp.int32))
    inputs = [qg]
    if has_sinks:
        inputs.append(sinks.astype(jnp.float32).reshape(kvh, g))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, Tq_pad, g, d), q.dtype),
        interpret=interpret,
    )(*prefetch, *inputs, *cache_args)
    # [kvh, Tq_pad, g, d] -> [Tq, h, d]
    return out.transpose(1, 0, 2, 3).reshape(Tq_pad, h, d)[:Tq]


def sharded_ragged_paged_attention(
    mesh: Mesh,
    tp_axis: str,
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_starts: jax.Array,
    q_lens: jax.Array,
    seq_lens: jax.Array,
    *,
    windows: jax.Array = None,
    sinks: jax.Array = None,
    **kw,
) -> jax.Array:
    """TP-sharded wrapper: attention is head-wise independent, so each TP
    shard runs the kernel on its own heads (q sharded on h, caches on kvh,
    sink logits on their head dim; per-row windows replicate). shard_map
    because GSPMD cannot partition a custom call — the same treatment as
    the split kernels' sharded wrappers."""
    if mesh.shape[tp_axis] == 1:
        return ragged_paged_attention(
            q, k_cache, v_cache, block_tables, q_starts, q_lens, seq_lens,
            windows=windows, sinks=sinks, **kw,
        )
    cache_spec = P(None, None, tp_axis, None)
    if is_quantized(k_cache):
        # spec tree mirrors the QuantizedKV pytree (payload on kv_heads,
        # scale rows on their kv-head dim) — same as the decode kernel
        cache_spec = QuantizedKV(cache_spec, P(None, tp_axis))
    args = [q, k_cache, v_cache, block_tables, q_starts, q_lens, seq_lens]
    specs = [
        P(None, tp_axis, None),
        cache_spec,
        cache_spec,
        P(None, None),
        P(None),
        P(None),
        P(None),
    ]
    has_window = windows is not None
    has_sinks = sinks is not None
    if has_window:
        args.append(windows)
        specs.append(P(None))
    if has_sinks:
        args.append(sinks)
        specs.append(P(tp_axis))

    def run(q, kc, vc, tables, qs, ql, sl, *rest):
        rest = list(rest)
        win = rest.pop(0) if has_window else None
        snk = rest.pop(0) if has_sinks else None
        return ragged_paged_attention(
            q, kc, vc, tables, qs, ql, sl, windows=win, sinks=snk, **kw
        )

    fn = shard_map(
        run,
        mesh=mesh,
        in_specs=tuple(specs),
        out_specs=P(None, tp_axis, None),
        check_vma=False,
    )
    return fn(*args)
