"""Deterministic kernel-side perf accounting: FLOP/HBM-byte counts.

The device bench is unreliable on this image (BENCH_NOTES: one clean
datapoint in five runs), so kernel work iterates against MODELED bytes
instead of measured seconds — the kernel-side half of the ROADMAP item 5
perf gate. Two complementary sources:

- :func:`jaxpr_counts` traces a jitted fn and walks the jaxpr, tallying
  MXU FLOPs (``dot_general``) and memory-moving op bytes (gather / scatter /
  dynamic slices / concatenate) op by op. ``pallas_call`` eqns are opaque to
  XLA's view of bytes (the kernel drives its own DMAs), so they are
  surfaced as entries for the caller to price with the analytic models;
- the analytic models below price the paged-attention DMA traffic of the
  three Pallas kernels exactly — pages touched (window-skipped pages
  excluded for sliding-window rows), scale rows, q/o streams, and the
  gather copies the split path pays that the unified kernel does not —
  parameterized by the concrete per-row (query_len, seq_len[, window])
  mix. Spec-decode verify rows (query_len = k+1) price against the
  retired split prefix-extend launch (:func:`spec_verify_vs_split`).

``bench.py`` folds :func:`mixed_vs_split` into BENCH JSON as
``detail.kernel_bytes`` and ``tests/test_unified_attention.py`` gates
mixed <= split on every PR, so a byte regression in the unified path fails
tier-1 without any hardware in the loop.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Sequence, Tuple

SCALE_BYTES = 4  # f32 per-block-per-kv-head scale rows (ops/quant.py)

# primitives whose cost is dominated by the bytes they move; priced as
# sum of operand + result nbytes
_MEMORY_PRIMS = {
    "gather", "scatter", "scatter-add", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "take", "take_along_axis",
}


# --------------------------------------------------------------- jaxpr walk
def _aval_bytes(aval) -> int:
    try:
        return int(math.prod(aval.shape)) * aval.dtype.itemsize
    except Exception:
        return 0


def _dot_flops(eqn) -> int:
    """2*M*N*K (times batch) for one dot_general."""
    (lhs, rhs) = eqn.invars[:2]
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    lshape = lhs.aval.shape
    rshape = rhs.aval.shape
    contract = math.prod(lshape[i] for i in lc) if lc else 1
    batch = math.prod(lshape[i] for i in lb) if lb else 1
    m = math.prod(
        s for i, s in enumerate(lshape) if i not in lc and i not in lb
    )
    n = math.prod(
        s for i, s in enumerate(rshape) if i not in rc and i not in rb
    )
    return 2 * batch * m * n * contract


def _walk(jaxpr, acc: Dict[str, Any]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            f = _dot_flops(eqn)
            acc["flops"] += f
            acc["by_op"][name] = acc["by_op"].get(name, 0) + f
        elif name == "pallas_call":
            info = eqn.params.get(
                "name_and_src_info", eqn.params.get("name", "")
            )
            acc["pallas_calls"].append({
                "name": str(info).split(" at ")[0] or "pallas_call",
                "in_shapes": [tuple(v.aval.shape) for v in eqn.invars],
                "out_shapes": [tuple(v.aval.shape) for v in eqn.outvars],
            })
        elif name in _MEMORY_PRIMS:
            b = sum(_aval_bytes(v.aval) for v in eqn.invars)
            b += sum(_aval_bytes(v.aval) for v in eqn.outvars)
            acc["hbm_bytes"] += b
            acc["by_op"][name] = acc["by_op"].get(name, 0) + b
        # recurse into sub-jaxprs (jit/scan/cond/while/shard_map bodies)
        for sub in eqn.params.values():
            if hasattr(sub, "jaxpr"):
                inner = sub.jaxpr if hasattr(sub.jaxpr, "eqns") else sub
                _walk(inner, acc)
            elif isinstance(sub, (list, tuple)):
                for s in sub:
                    if hasattr(s, "jaxpr"):
                        _walk(s.jaxpr, acc)


def jaxpr_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Trace ``fn(*args, **kwargs)`` and return op-level cost tallies:
    ``{"flops", "hbm_bytes", "by_op", "pallas_calls"}``. FLOPs come from
    dot_general shapes; hbm_bytes from memory-moving primitives;
    ``pallas_calls`` lists the opaque kernel launches for the caller to
    price with the analytic models (their DMA traffic is invisible to the
    jaxpr)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    acc: Dict[str, Any] = {
        "flops": 0, "hbm_bytes": 0, "by_op": {}, "pallas_calls": [],
    }
    _walk(closed.jaxpr, acc)
    return acc


# ------------------------------------------------------- analytic DMA models
def _pages(seq_len: int, bs: int) -> int:
    return -(-max(int(seq_len), 0) // bs)


def unified_attention_bytes(
    rows: Sequence[Tuple[int, ...]],   # (query_len, seq_len[, window])
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    kv_itemsize: int = 2,              # bf16 pages; 1 for int8
    q_itemsize: int = 2,
    quantized: bool = False,
) -> int:
    """HBM bytes one unified ragged launch moves (ops/pallas_unified):
    each active row's LIVE pages stream once per kv head as per-head slices
    (total = the full page bytes), plus int8 scale rows, plus the packed
    q read and o write. No gather, no per-q-tile context re-read.

    A row may carry a third element — a positive sliding-window bound —
    in which case the kernel never DMAs the pages the window aged out:
    live pages start at ``max(ctx_start - w + 1, 0) // bs`` (page-granular,
    matching the kernel's windowed head skip)."""
    total_q = sum(max(r[0], 0) for r in rows)
    kv = 0
    for row in rows:
        q_len, seq_len = row[0], row[1]
        w = row[2] if len(row) > 2 else 0
        if q_len <= 0 or seq_len <= 0:
            continue
        p = _pages(seq_len, block_size)
        if w and w > 0:
            ctx_start = seq_len - q_len
            p -= max(ctx_start - w + 1, 0) // block_size
        kv += 2 * p * block_size * kv_heads * head_dim * kv_itemsize
        if quantized:
            # the kernel DMAs the full [kvh] scale row per page per kv head
            kv += 2 * p * kv_heads * kv_heads * SCALE_BYTES
    qo = 2 * total_q * num_heads * head_dim * q_itemsize
    return kv + qo


def split_prefill_bytes(
    chunk_len: int,
    total_len: int,
    table_blocks: int,                 # gather width: max_blocks_per_seq
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
    q_tile: int = 128,
    bucket: int = None,
) -> int:
    """HBM bytes the SPLIT prefill path moves for one chunk: gather_kv
    materializes the FULL padded table (read + write, both K and V), then
    the flash-extend kernel streams the gathered context once per q tile
    (its grid re-reads every kv tile for each of the chunk's q tiles),
    plus the q read / o write at the bucketed width."""
    del total_len  # the split gather width is the PADDED table, not the
    # real context — that is exactly the waste being priced
    S_pad = bucket if bucket is not None else chunk_len
    T = table_blocks * block_size
    ctx_elems = T * kv_heads * head_dim
    gather = 2 * 2 * ctx_elems * kv_itemsize      # K+V, read+write
    if quantized:
        gather += 2 * 2 * table_blocks * kv_heads * SCALE_BYTES
    nq = -(-S_pad // q_tile)
    kernel_kv = 2 * nq * ctx_elems * kv_itemsize
    if quantized:
        # per-position scale columns stream with the tiles
        kernel_kv += 2 * nq * T * kv_heads * SCALE_BYTES
    qo = 2 * S_pad * num_heads * head_dim * q_itemsize
    return gather + kernel_kv + qo


def split_decode_bytes(
    seq_lens: Iterable[int],
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
    window: int = None,
) -> int:
    """HBM bytes one ragged decode launch moves (ops/pallas_attention):
    each row's real pages once (+ scale rows), one query token per row.
    ``window``: the split windowed-decode path gathers only the trailing
    ``ceil(w / bs) + 1`` blocks (ops/attention.paged_decode_attention)."""
    kv = 0
    n = 0
    for L in seq_lens:
        if L <= 0:
            continue
        n += 1
        p = _pages(L, block_size)
        if window is not None and window > 0:
            p = min((window + block_size - 1) // block_size + 1, p)
        kv += 2 * p * block_size * kv_heads * head_dim * kv_itemsize
        if quantized:
            kv += 2 * p * kv_heads * SCALE_BYTES
    qo = 2 * n * num_heads * head_dim * q_itemsize
    return kv + qo


def split_extend_bytes(
    n_rows: int,
    s_new: int,                        # candidate tokens per row (spec: k+1)
    table_blocks: int,                 # gather width: max_blocks_per_seq
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
) -> int:
    """HBM bytes the SPLIT prefix-extend launch moves for a batch — the
    pre-unification spec-decode verify pass
    (ops/attention.paged_extend_attention): per row, ``gather_kv``
    materializes the FULL padded table (read + write, K and V), the dense
    extend scores read the gathered context once more, plus the q read /
    o write over the ``s_new`` candidate positions."""
    T = table_blocks * block_size
    ctx_elems = T * kv_heads * head_dim
    per_row = 2 * 2 * ctx_elems * kv_itemsize   # gather: K+V, read+write
    per_row += 2 * ctx_elems * kv_itemsize      # dense scores re-read K+V
    if quantized:
        per_row += 2 * 2 * table_blocks * kv_heads * SCALE_BYTES
    qo = 2 * s_new * num_heads * head_dim * q_itemsize
    return n_rows * (per_row + qo)


def spec_verify_vs_split(
    spec_k: int,
    decode_seq_lens: Sequence[int],
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    max_blocks_per_seq: int,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
) -> Dict[str, Any]:
    """Price ONE spec-decode verify pass as unified ragged rows
    (``query_len = k+1`` per row, candidates at the context tail) against
    the split prefix-extend launch it replaced. Returned as a
    ``detail.kernel_bytes.families`` entry by ``bench.py``; tier-1 asserts
    the ratio <= 1.0 — strictly stronger than the acceptance bound (the
    split side here omits the decode dispatch the pair formulation adds).
    """
    rows = [(spec_k + 1, int(L) + spec_k) for L in decode_seq_lens if L > 0]
    kw = dict(
        block_size=block_size, kv_heads=kv_heads, num_heads=num_heads,
        head_dim=head_dim, kv_itemsize=kv_itemsize, q_itemsize=q_itemsize,
        quantized=quantized,
    )
    unified = unified_attention_bytes(rows, **kw)
    split = split_extend_bytes(
        len(rows), spec_k + 1, max_blocks_per_seq, **kw
    )
    return {
        "unified_verify_bytes": int(unified),
        "split_extend_bytes": int(split),
        "ratio": round(unified / split, 4) if split else 0.0,
        "rows": len(rows),
        "spec_k": int(spec_k),
        "quantized": bool(quantized),
    }


# ------------------------------------------------- analytic transfer model
def streamed_transfer_model(
    prompt_tokens: int,
    *,
    block_size: int,
    prefill_chunk: int,
    kv_bytes_per_block: int,
    bandwidth_bytes_s: float,
    prefill_chunk_s: float,
    window_blocks: int = 8,
    handshake_s: float = 0.0,
    decode_step_s: float = 0.0,
) -> Dict[str, Any]:
    """Deterministic TTFT model of blocking vs streamed disagg KV transfer.

    The prefill side computes ``ceil(prompt/chunk)`` chunks, each taking
    ``prefill_chunk_s``; a chunk's blocks become transferable when it lands
    (the engine content-addresses them per chunk). The decode side cannot
    produce its first token until every prompt block arrived (+ one decode
    step).

    - blocking: the pull starts only after the LAST chunk — TTFT pays
      prefill then the whole serialized wire transfer back to back.
    - streamed: windows of ``window_blocks`` ship as soon as their blocks
      are committed, on one wire (transfers serialize with each other but
      overlap prefill compute) — TTFT pays prefill plus only the wire TAIL
      that could not hide under compute.

    Pure function of its arguments (the tier-1 gate asserts streamed <=
    blocking across a parameter grid; ``bench.py`` folds one call at the
    bench shapes into BENCH JSON as ``detail.transfer``).
    """
    blocks = max(_pages(prompt_tokens, block_size), 0)
    chunks = max(_pages(prompt_tokens, prefill_chunk), 1)
    prefill_s = chunks * prefill_chunk_s
    bw = max(float(bandwidth_bytes_s), 1.0)
    total_bytes = blocks * kv_bytes_per_block
    blocking_ttft = prefill_s + handshake_s + total_bytes / bw + decode_step_s
    # streamed pipeline: walk windows in commit order; a window starts when
    # both its last block is committed and the wire is free
    blocks_per_chunk = prefill_chunk // block_size
    wire_free = handshake_s
    done_at = handshake_s  # no blocks -> transfer adds nothing
    sent = 0
    while sent < blocks:
        take = min(window_blocks, blocks - sent)
        last_block = sent + take  # 1-based index of the window's last block
        commit_chunk = _pages(last_block, blocks_per_chunk) if blocks_per_chunk else 1
        committed_at = min(commit_chunk, chunks) * prefill_chunk_s
        start = max(wire_free, committed_at)
        wire_free = start + take * kv_bytes_per_block / bw
        done_at = wire_free
        sent += take
    streamed_ttft = max(done_at, prefill_s) + decode_step_s
    transfer_s = total_bytes / bw
    hidden = max(blocking_ttft - streamed_ttft, 0.0)
    return {
        "prompt_tokens": int(prompt_tokens),
        "blocks": int(blocks),
        "prefill_chunks": int(chunks),
        "prefill_s": round(prefill_s, 6),
        "transfer_s": round(transfer_s, 6),
        "bytes": int(total_bytes),
        "bandwidth_bytes_s": round(bw, 1),
        "window_blocks": int(window_blocks),
        "blocking_ttft_s": round(blocking_ttft, 6),
        "streamed_ttft_s": round(streamed_ttft, 6),
        "speedup": round(blocking_ttft / streamed_ttft, 4)
        if streamed_ttft > 0 else 1.0,
        # fraction of the wire time hidden under prefill compute
        "overlap_fraction": round(hidden / transfer_s, 4)
        if transfer_s > 0 else 0.0,
    }


# tier read-latency priors (seconds per block window): G2 host DRAM is a
# memcpy, G3 disk a file read. Only the RATIO to wire time matters for the
# decision; absolute values are deliberately conservative.
TIER_READ_S_PER_BLOCK = {"g2": 2e-4, "g3": 2e-3}


def fetch_vs_recompute(
    num_blocks: int,
    *,
    block_size: int,
    kv_bytes_per_block: int,
    bandwidth_bytes_s: float,
    prefill_base_s: float,
    prefill_per_token_s: float,
    tier: str = "g2",
    window_blocks: int = 8,
    handshake_s: float = 0.01,
    tier_read_s_per_block: float = None,
    margin: float = 1.0,
) -> Dict[str, Any]:
    """Deterministic price of onboarding ``num_blocks`` sealed KV blocks
    from a peer worker's G2/G3 tier vs recomputing them as local prefill —
    the global-directory routing decision (ROADMAP item 3).

    Fetch is pipelined in ``window_blocks`` windows over one wire: the
    peer reads a window from its tier while the previous window is in
    flight, so steady state pays ``max(wire, tier read)`` per window plus
    the first window's un-overlapped tier read and the handshake.
    Recompute pays the local prefill model for the same tokens.

    ``fetch`` is chosen iff ``fetch_s <= margin * recompute_s`` — so
    "wherever the router chooses fetch, fetch is no slower than
    recompute" holds *by construction* for ``margin <= 1`` (the tier-1
    grid gate asserts exactly this over wire/tier/block-count
    combinations). Pure function of its arguments; ``bench.py`` feeds the
    same model from the wire-bandwidth EWMA at run time.
    """
    n = max(int(num_blocks), 0)
    bw = max(float(bandwidth_bytes_s), 1.0)
    read_s = (
        float(tier_read_s_per_block)
        if tier_read_s_per_block is not None
        else TIER_READ_S_PER_BLOCK.get(tier, TIER_READ_S_PER_BLOCK["g3"])
    )
    win = max(int(window_blocks), 1)
    n_windows = -(-n // win) if n else 0
    window_wire_s = win * kv_bytes_per_block / bw
    window_read_s = win * read_s
    if n:
        # last window may be partial; pricing it full keeps the model
        # monotone in num_blocks (a conservative over-estimate of fetch)
        fetch_s = (
            handshake_s
            + window_read_s
            + n_windows * max(window_wire_s, window_read_s)
        )
    else:
        fetch_s = 0.0
    recompute_s = (
        prefill_base_s + n * block_size * prefill_per_token_s if n else 0.0
    )
    fetch_wins = n > 0 and fetch_s <= margin * recompute_s
    return {
        "num_blocks": n,
        "tier": tier,
        "bytes": n * int(kv_bytes_per_block),
        "bandwidth_bytes_s": round(bw, 1),
        "window_blocks": win,
        "fetch_s": round(fetch_s, 6),
        "recompute_s": round(recompute_s, 6),
        "fetch_wins": bool(fetch_wins),
        "margin": float(margin),
        "speedup": round(recompute_s / fetch_s, 4) if fetch_s > 0 else 1.0,
    }


def predict_step_seconds(
    rows: Sequence[Tuple[int, ...]],   # (query_len, seq_len[, window])
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    hbm_bytes_s: float,
    dispatch_s: float = 0.0,
    weight_bytes: int = 0,
    layers: int = 1,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
) -> float:
    """Roofline floor for one unified-attention engine step in SECONDS:
    the step's modeled HBM traffic (attention pages via
    :func:`unified_attention_bytes`, once per layer, + one weight stream)
    over the device's sustained HBM bandwidth, plus a fixed host dispatch
    overhead.

    This is the expectation side of the ``cost_model_drift`` degradation
    detector (runtime/health.py): the engine's measured step wall time is
    compared against this prediction for the same row mix, and a worker
    whose ratio climbs while its neighbours' stays flat has a local
    problem (thermal throttle, noisy neighbour, dying HBM) that no
    fleet-wide average would localize. A memory-bound floor is exactly
    what is wanted for that comparison: real steps run a bounded factor
    above it, and the detector trips on the RATIO drifting, not on the
    absolute value.
    """
    att_bytes = unified_attention_bytes(
        rows, block_size=block_size, kv_heads=kv_heads, num_heads=num_heads,
        head_dim=head_dim, kv_itemsize=kv_itemsize, q_itemsize=q_itemsize,
        quantized=quantized,
    )
    bw = max(float(hbm_bytes_s), 1.0)
    total = att_bytes * max(int(layers), 1) + max(int(weight_bytes), 0)
    return total / bw + max(dispatch_s, 0.0)


def mixed_vs_split(
    chunk_len: int,
    chunk_total_len: int,
    decode_seq_lens: Sequence[int],
    *,
    block_size: int,
    kv_heads: int,
    num_heads: int,
    head_dim: int,
    max_blocks_per_seq: int,
    kv_itemsize: int = 2,
    q_itemsize: int = 2,
    quantized: bool = False,
    bucket: int = None,
    window: int = None,
) -> Dict[str, Any]:
    """Price ONE mixed continuous-batching step against the equivalent
    split pair (one prefill-chunk dispatch + one decode dispatch over the
    same rows). Returns the byte counts and their ratio — the deterministic
    gate `bench.py` emits as ``detail.kernel_bytes`` and tier-1 asserts
    stays <= 1.0. ``window``: price every row with a sliding-window bound
    (gpt-oss/gemma sliding layers) — the unified side skips aged-out pages,
    the split decode side gathers only the trailing window blocks."""
    w = int(window) if window else 0
    rows: List[Tuple[int, int, int]] = [(chunk_len, chunk_total_len, w)]
    rows += [(1, int(L), w) for L in decode_seq_lens]
    kw = dict(
        block_size=block_size, kv_heads=kv_heads, num_heads=num_heads,
        head_dim=head_dim, kv_itemsize=kv_itemsize, q_itemsize=q_itemsize,
        quantized=quantized,
    )
    mixed = unified_attention_bytes(rows, **kw)
    split = split_prefill_bytes(
        chunk_len, chunk_total_len, max_blocks_per_seq, bucket=bucket, **kw
    ) + split_decode_bytes(decode_seq_lens, window=window, **kw)
    out = {
        "mixed_step_bytes": int(mixed),
        "split_pair_bytes": int(split),
        "ratio": round(mixed / split, 4) if split else 0.0,
        "rows": len(rows),
        "quantized": bool(quantized),
    }
    if window is not None:
        out["window"] = int(window)
    return out
