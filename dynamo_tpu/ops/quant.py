"""Int8 paged-KV quantization: the storage format and its numerics.

Decode is HBM-bandwidth-bound (BENCH_NOTES: the decode program sits at ~77%
of the roofline and paged-KV reads dominate the per-step bytes at batch).
Storing the paged cache as int8 with per-block-per-kv-head float32 scales
halves the KV bytes on every path that touches them — the HBM page reads in
both attention kernels, the disagg transfer wire, and the KVBM host/disk
tiers — and doubles effective KV capacity. This is the standard bandwidth
lever behind Ragged Paged Attention's TPU kernel work (PAPERS: arxiv
2604.15464) and FlowKV's low-latency KV transfer (arxiv 2504.03775).

Format, shared by every layer of the stack (device cache, Pallas kernels,
transfer wire, KVBM block codec):

    payload : int8  [..., block_size, kv_heads, head_dim]
    scale   : f32   [..., kv_heads]      (amax over the block's positions
                                          and head_dim, divided by 127)

Quantization is symmetric round-to-nearest:  q = rint(x / scale) in
[-127, 127];  dequant = q * scale.  Two properties tests rely on:

  - round-trip error per element is bounded by scale/2 = amax/254;
  - for a SCALE-SATURATED block (fresh quantize_blocks output: max|q| ==
    127 by construction) dequantize -> requantize reproduces (payload,
    scale) BIT-EXACTLY — the recomputed amax equals 127*scale and the ints
    re-round to themselves — which is what makes float<->int8 engine
    handoffs over the transfer plane lossless past the first quantization.
    A block whose scale later GREW via requantize_token (a decode write
    raised the amax) has max|q| < 127, so a float round trip of it is
    quantization-tolerance-equivalent rather than bit-exact; int8<->int8
    moves (transfer, KVBM) ship the pair untouched and stay bit-exact
    always.

``QuantizedKV`` is the device-side pair, registered as a JAX pytree so the
engine's cache lists, jit donation, shard_map specs, and the multi-layer
scan carries treat it exactly like the raw array it replaces.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# dtype of the per-block-per-kv-head scale rows everywhere (device, wire,
# KVBM codec). int8 payload + f32 scales is the whole format.
SCALE_DTYPE = np.dtype(np.float32)
KV_DTYPES = ("model", "int8")


def resolve_kv_dtype(value: str) -> str:
    """Resolve a config ``kv_dtype`` to one of KV_DTYPES. ``auto`` defers to
    the DTPU_KV_DTYPE env (default: model dtype — behavior unchanged)."""
    v = (value or "auto").lower()
    if v == "auto":
        v = os.environ.get("DTPU_KV_DTYPE", "model").lower() or "model"
    if v in ("none", "float", "fp", "cache"):
        v = "model"
    if v not in KV_DTYPES:
        raise ValueError(
            f"unknown kv_dtype {value!r} (DTPU_KV_DTYPE?): expected one of "
            f"{KV_DTYPES} or 'auto'"
        )
    return v


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedKV:
    """One paged KV cache array quantized to int8 + per-block scales.

    data  : int8 [num_blocks, block_size, kv_heads, head_dim]
    scale : f32  [num_blocks, kv_heads]

    ``.shape``/``.dtype`` mirror the payload so shape-probing call sites
    (``k_cache.shape[1]`` for block_size etc.) work unchanged.
    """

    data: Any
    scale: Any

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


def is_quantized(cache: Any) -> bool:
    return isinstance(cache, QuantizedKV)


# ---------------------------------------------------------------- jnp kernels
def quantize_blocks(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., bs, kvh, d] float -> (int8 payload, f32 scale [..., kvh]).

    amax reduces over the block's positions AND head_dim (one scale per
    kv head per block); an all-zero block gets scale 0 and payload 0, and
    dequantizes to exact zeros."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-3, -1))              # [..., kvh]
    scale = amax / 127.0
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(
        jnp.rint(xf * inv[..., None, :, None]), -127.0, 127.0
    ).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_blocks(q: jax.Array, scale: jax.Array) -> jax.Array:
    """(int8 [..., bs, kvh, d], f32 [..., kvh]) -> f32 [..., bs, kvh, d]."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


def requantize_token(
    blk_q: jax.Array,      # int8 [..., bs, kvh, d] current block contents
    blk_scale: jax.Array,  # f32  [..., kvh] current block scale
    x_new: jax.Array,      # [..., kvh, d] the one new row (float)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Decode-write numerics: grow the block scale to cover the new row and
    rescale the existing ints once (ratio <= 1; when the scale is unchanged
    — the common case — ratio == 1 and the rescale is a bit-exact no-op).
    Returns (rescaled block ints, new scale, the new row quantized)."""
    a_new = jnp.max(jnp.abs(x_new.astype(jnp.float32)), axis=-1)   # [..., kvh]
    s_new = jnp.maximum(blk_scale, a_new / 127.0)
    inv = jnp.where(s_new > 0, 1.0 / s_new, 0.0)
    ratio = blk_scale * inv                                        # <= 1
    blk = jnp.rint(
        blk_q.astype(jnp.float32) * ratio[..., None, :, None]
    ).astype(jnp.int8)
    q_new = jnp.clip(
        jnp.rint(x_new.astype(jnp.float32) * inv[..., None]), -127.0, 127.0
    ).astype(jnp.int8)
    return blk, s_new.astype(jnp.float32), q_new


# ---------------------------------------------------------------- np mirrors
def quantize_blocks_np(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side mirror of quantize_blocks (same formula, same rounding):
    used by the transfer client when importing float pages into an int8
    engine. Dequantize->requantize is bit-exact (see module docstring)."""
    xf = np.asarray(x, np.float32)
    amax = np.max(np.abs(xf), axis=(-3, -1))
    scale = (amax / 127.0).astype(np.float32)
    inv = np.where(scale > 0, 1.0 / scale, 0.0).astype(np.float32)
    q = np.clip(
        np.rint(xf * inv[..., None, :, None]), -127.0, 127.0
    ).astype(np.int8)
    return q, scale


def dequantize_blocks_np(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * np.asarray(scale, np.float32)[..., None, :, None]
