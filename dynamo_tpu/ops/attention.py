"""Attention ops for paged-KV serving: prefill, prefix-extend, paged decode.

Pure-JAX reference implementations (XLA fuses these well on TPU already);
the Pallas ragged-paged-attention kernel in ops/pallas_attention.py is a
drop-in replacement on the same interfaces for the decode hot path.

Replaces what the reference delegates to engine-internal kernels (vLLM
paged attention / FlashInfer); the CUDA block-copy kernel analog lives in
ops/block_copy.py.

Layout: paged KV cache per layer is ``[num_blocks, block_size, kv_heads,
head_dim]`` — block-major so a block is contiguous in HBM (transfer-friendly,
like the reference KVBM's fully-contiguous layout, lib/llm/src/block_manager/
layout.rs) with heads minor to keep per-head slices dense for TP sharding.

Every op that touches the cache also accepts the int8 form (ops/quant.py
``QuantizedKV``: int8 payload + per-block-per-kv-head f32 scales). Writes
quantize on the way in; gathers dequantize on the way out — so this file is
the numerics reference the Pallas kernels and the CPU tier-1 tests pin
against, float and int8 alike.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .quant import (
    QuantizedKV,
    dequantize_blocks,
    is_quantized,
    quantize_blocks,
    requantize_token,
)

NEG_INF = -1e30


def _softcap(scores: jax.Array, cap: Optional[float]) -> jax.Array:
    """Gemma-2 style attention-logit softcapping: cap * tanh(scores/cap),
    applied post-scale and pre-mask (matches the HF reference ordering).
    None = untouched."""
    if cap is None:
        return scores
    return jnp.tanh(scores / cap) * cap


def _gqa_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """q [S,h,d] x k [T,kvh,d] -> scores [S,h,T] with GQA head grouping."""
    S, h, d = q.shape
    T, kvh, _ = k.shape
    g = h // kvh
    qg = q.reshape(S, kvh, g, d)
    scores = jnp.einsum("skgd,tkd->skgt", qg.astype(jnp.float32), k.astype(jnp.float32))
    return scores.reshape(S, h, T)


def _gqa_values(weights: jax.Array, v: jax.Array) -> jax.Array:
    """weights [S,h,T] x v [T,kvh,d] -> out [S,h,d]."""
    S, h, T = weights.shape
    _, kvh, d = v.shape
    g = h // kvh
    wg = weights.reshape(S, kvh, g, T)
    out = jnp.einsum("skgt,tkd->skgd", wg, v.astype(jnp.float32))
    return out.reshape(S, h, d)


def _sink_softmax(scores: jax.Array, sinks: jax.Array) -> jax.Array:
    """Softmax over the key axis with attention-sink logits in the
    DENOMINATOR only (gpt-oss: a virtual key whose probability mass is
    dropped, damping every real weight). scores [..., T]; ``sinks``
    broadcastable to scores' leading dims."""
    m = jnp.maximum(jnp.max(scores, axis=-1), sinks)
    p = jnp.exp(scores - m[..., None])
    denom = jnp.sum(p, axis=-1) + jnp.exp(sinks - m)
    return p / denom[..., None]


def causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    window: Optional[int] = None,
    sinks: Optional[jax.Array] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """Plain causal self-attention for a single contiguous sequence.

    q,k,v: [S, heads/kv_heads, head_dim] -> [S, heads, head_dim].
    ``window``: sliding-window attention — key j visible to query i iff
    i - window < j <= i. ``sinks``: per-head [h] attention-sink logits
    (gpt-oss) folded into the softmax denominator."""
    S = q.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    scores = _softcap(_gqa_scores(q, k) * scale, softcap)
    qi, kj = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
    causal = kj <= qi
    if window is not None:
        causal &= kj > qi - window
    scores = jnp.where(causal[:, None, :], scores, NEG_INF)
    if sinks is None:
        weights = jax.nn.softmax(scores, axis=-1)
    else:
        weights = _sink_softmax(scores, sinks.astype(jnp.float32))
    return _gqa_values(weights, v).astype(q.dtype)


def extend_attention(
    q: jax.Array,            # [S_new, h, d] queries for the new suffix
    k_ctx: jax.Array,        # [T_max, kvh, d] gathered context incl. new keys
    v_ctx: jax.Array,        # [T_max, kvh, d]
    q_positions: jax.Array,  # [S_new] absolute positions of the queries
    total_len: jax.Array,    # scalar: valid length of the context
    window: Optional[int] = None,
    sinks: Optional[jax.Array] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """Prefix-extend attention: new tokens attend causally over (cached prefix
    + themselves). Used for prefill with device-side prefix-cache reuse and
    for chunked prefill continuation. Context is padded to T_max; invalid
    positions masked. ``window``/``sinks``: see causal_attention (the
    context layout is positional, so the window mask is absolute-position
    based)."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    T = k_ctx.shape[0]
    scores = _softcap(_gqa_scores(q, k_ctx) * scale, softcap)  # [S,h,T]
    key_pos = jnp.arange(T)
    valid = key_pos[None, :] < jnp.minimum(q_positions[:, None] + 1, total_len)
    if window is not None:
        valid &= key_pos[None, :] > q_positions[:, None] - window
    scores = jnp.where(valid[:, None, :], scores, NEG_INF)
    if sinks is None:
        weights = jax.nn.softmax(scores, axis=-1)
    else:
        weights = _sink_softmax(scores, sinks.astype(jnp.float32))
    return _gqa_values(weights, v_ctx).astype(q.dtype)


def gather_kv(
    k_cache: jax.Array,      # [num_blocks, block_size, kvh, d] (or QuantizedKV)
    v_cache: jax.Array,
    block_table: jax.Array,  # [max_blocks] int32 (padded with 0)
) -> Tuple[jax.Array, jax.Array]:
    """Gather one sequence's KV pages into contiguous [max_blocks*bs, kvh, d].

    Quantized caches dequantize during the gather (f32 out): the HBM read is
    still the int8 payload + tiny scale rows, which is where the bandwidth
    win lives; every consumer casts to f32 for the matmuls anyway."""
    bs = k_cache.shape[1]
    mb = block_table.shape[0]
    if is_quantized(k_cache):
        k = dequantize_blocks(
            k_cache.data[block_table], k_cache.scale[block_table]
        )
        v = dequantize_blocks(
            v_cache.data[block_table], v_cache.scale[block_table]
        )
    else:
        k = k_cache[block_table]  # [max_blocks, bs, kvh, d]
        v = v_cache[block_table]
    return (
        k.reshape(mb * bs, *k.shape[2:]),
        v.reshape(mb * bs, *v.shape[2:]),
    )


def gather_kv_quant(
    k_cache: QuantizedKV,
    v_cache: QuantizedKV,
    block_table: jax.Array,  # [max_blocks] int32
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Raw-int8 gather for kernels that dequantize in-register
    (ops/pallas_prefill): (k int8 [T, kvh, d], v int8, k_scales f32 [T, kvh],
    v_scales f32 [T, kvh]) with the per-block scales broadcast to positions."""
    bs = k_cache.shape[1]
    mb = block_table.shape[0]

    def pick(c):
        q = c.data[block_table].reshape(mb * bs, *c.shape[2:])
        s = jnp.broadcast_to(
            c.scale[block_table][:, None, :], (mb, bs, c.shape[2])
        ).reshape(mb * bs, c.shape[2])
        return q, s

    kq, ks = pick(k_cache)
    vq, vs = pick(v_cache)
    return kq, vq, ks, vs


def paged_decode_attention(
    q: jax.Array,             # [B, h, d] one query token per sequence
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks] int32
    seq_lens: jax.Array,      # [B] int32 context length incl. current token
    window: Optional[int] = None,
    sinks: Optional[jax.Array] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """Paged decode attention, batched: each query attends over its own pages.

    Pure-JAX formulation: per-sequence page gather via vmap; masked softmax.
    ``window``/``sinks``: see causal_attention. The decode query sits at
    position length-1, so the window admits key indices >= length - window.
    Sliding-window layers gather ONLY the window's trailing blocks (a
    static ceil(window/bs)+1 slice of the block table), so a 128-token
    window over a 128k context reads ~window keys, not the whole cache.
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    bs = k_cache.shape[1]
    if window is not None:
        wb = min((window + bs - 1) // bs + 1, block_tables.shape[1])

    def one(qb, table, length):
        if window is None:
            k, v = gather_kv(k_cache, v_cache, table)  # [T, kvh, d]
            key_pos = jnp.arange(k.shape[0])
            valid = key_pos < length
        else:
            # trailing-window gather: last wb table entries that cover
            # [length - window, length)
            nblocks = jnp.maximum((length + bs - 1) // bs, 1)
            start = jnp.maximum(nblocks - wb, 0)
            idx = start + jnp.arange(wb)
            sub = table[jnp.clip(idx, 0, table.shape[0] - 1)]
            k, v = gather_kv(k_cache, v_cache, sub)    # [wb*bs, kvh, d]
            key_pos = start * bs + jnp.arange(wb * bs)
            valid = (key_pos < length) & (key_pos >= length - window)
        h, d = qb.shape
        kvh = k.shape[1]
        g = h // kvh
        qg = qb.reshape(kvh, g, d)
        scores = _softcap(jnp.einsum(
            "kgd,tkd->kgt", qg.astype(jnp.float32), k.astype(jnp.float32)
        ) * scale, softcap)                             # [kvh, g, T]
        scores = jnp.where(valid[None, None, :], scores, NEG_INF)
        if sinks is None:
            weights = jax.nn.softmax(scores, axis=-1)
        else:
            weights = _sink_softmax(
                scores, sinks.astype(jnp.float32).reshape(kvh, g)
            )
        out = jnp.einsum("kgt,tkd->kgd", weights, v.astype(jnp.float32))
        return out.reshape(h, d)

    return jax.vmap(one)(q, block_tables, seq_lens).astype(q.dtype)


def write_prefill_kv(
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d]
    v_cache: jax.Array,
    k_new: jax.Array,         # [S_pad, kvh, d] (S_pad multiple of bs)
    v_new: jax.Array,
    block_ids: jax.Array,     # [S_pad // bs] destination blocks for the span
) -> Tuple[jax.Array, jax.Array]:
    """Scatter a contiguous span of new KV into its pages (prefill path).

    The caller pads S to a block multiple and supplies one destination block
    per chunk; padding rows land in a scratch block (block 0 by convention is
    reserved as scratch so garbage writes are harmless).

    Quantized caches quantize-on-write: prefill writes whole blocks, so the
    per-block amax (and thus the scale) is computed in one shot — no rescale
    ever needed on this path. The amax covers EVERY row passed, so callers
    must zero bucket-padding rows first (the engine's prefill attend does)
    or pad activations inflate the real tokens' scale."""
    bs = k_cache.shape[1]
    S = k_new.shape[0]
    k_blocks = k_new.reshape(S // bs, bs, *k_new.shape[1:])
    v_blocks = v_new.reshape(S // bs, bs, *v_new.shape[1:])
    if is_quantized(k_cache):
        kq, ks = quantize_blocks(k_blocks)
        vq, vs = quantize_blocks(v_blocks)
        return (
            QuantizedKV(
                k_cache.data.at[block_ids].set(kq),
                k_cache.scale.at[block_ids].set(ks),
            ),
            QuantizedKV(
                v_cache.data.at[block_ids].set(vq),
                v_cache.scale.at[block_ids].set(vs),
            ),
        )
    return k_cache.at[block_ids].set(k_blocks), v_cache.at[block_ids].set(v_blocks)


def write_decode_kv(
    k_cache: jax.Array,
    v_cache: jax.Array,
    k_new: jax.Array,         # [B, kvh, d]
    v_new: jax.Array,
    block_ids: jax.Array,     # [B] destination block of each seq's current pos
    offsets: jax.Array,       # [B] offset within the block
) -> Tuple[jax.Array, jax.Array]:
    """Scatter one token per sequence into its page slot (decode path).

    Quantized caches do a read-modify-write of the ONE destination block per
    row: the block scale grows to cover the new token and the existing ints
    rescale once (ops/quant.requantize_token — a bit-exact no-op whenever the
    scale is unchanged, the common case). A write at offset 0 is the FIRST
    row of a freshly-(re)allocated block, so the inherited scale is a stale
    leftover from the block's previous occupant and is reset — otherwise a
    recycled block that once held large activations would quantize a small
    new token to zero. Inactive rows all target scratch block 0;
    duplicate-index write order there is undefined and harmless."""
    if is_quantized(k_cache):
        B = k_new.shape[0]
        rows = jnp.arange(B)
        fresh = (offsets == 0)[:, None]  # [B, 1] broadcast over kvh

        def wr(cache, x_new):
            s_base = jnp.where(fresh, 0.0, cache.scale[block_ids])
            blk, s_new, q_new = requantize_token(
                cache.data[block_ids], s_base, x_new
            )
            blk = blk.at[rows, offsets].set(q_new)
            return QuantizedKV(
                cache.data.at[block_ids].set(blk),
                cache.scale.at[block_ids].set(s_new),
            )

        return wr(k_cache, k_new), wr(v_cache, v_new)
    return (
        k_cache.at[block_ids, offsets].set(k_new),
        v_cache.at[block_ids, offsets].set(v_new),
    )


def ragged_paged_attention(
    q: jax.Array,             # [Tq, h, d] densely packed ragged queries
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d] (or QuantizedKV)
    v_cache: jax.Array,
    block_tables: jax.Array,  # [R, max_blocks] int32
    q_starts: jax.Array,      # [R] int32 offset of row r's segment in q
    q_lens: jax.Array,        # [R] int32 segment length (0 = empty row)
    seq_lens: jax.Array,      # [R] int32 context length incl. the row's
                              #     q_lens new tokens
    window: Optional[int] = None,
    sinks: Optional[jax.Array] = None,
    softcap: Optional[float] = None,
    windows: Optional[jax.Array] = None,
) -> jax.Array:
    """Unified ragged paged attention, pure-JAX reference twin of
    ``ops.pallas_unified.ragged_paged_attention``.

    One call serves an arbitrary mix of prefill chunks and decode tokens:
    each ROW r owns the query tokens ``q[q_starts[r] : q_starts[r]+q_lens[r]]``
    (its new tokens, sitting at the TAIL of its context — token i of the
    segment is at absolute position ``seq_lens[r] - q_lens[r] + i``) and
    attends causally over its own pages. A decode row is ``q_len == 1``; a
    prefill chunk is ``q_len == chunk_len``; a spec-decode verify pass is a
    row with ``q_len == k+1``. Segments must be disjoint (gaps are fine —
    padding rows between segments belong to no row); ``q_len <= seq_len``
    per row. Tokens outside every segment, and rows with ``q_len == 0`` or
    ``seq_len == 0`` (inactive slots), return ZEROS.

    ``window`` applies one sliding-window bound to every row; ``windows``
    ([R] int32, ``<= 0`` = full attention) sets it per row — the form the
    Pallas kernel takes. ``sinks``/``softcap``: see causal_attention.

    This is the numerics reference the Pallas unified kernel pins against in
    interpret mode; the engine's mixed prefill+decode step uses it directly
    when ``use_pallas`` is off. O(R * Tq * T) — every row scores the whole
    packed buffer and masks — so it is a reference, not a fast path."""
    Tq = q.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    idx = jnp.arange(Tq)
    if windows is None and window is not None:
        windows = jnp.full(block_tables.shape[0], window, jnp.int32)
    windowed = windows is not None

    def one(table, q_start, q_len, seq_len, w):
        k, v = gather_kv(k_cache, v_cache, table)   # [T, kvh, d]
        local = idx - q_start
        member = (local >= 0) & (local < q_len) & (seq_len > 0)
        q_pos = seq_len - q_len + local
        scores = _softcap(_gqa_scores(q, k) * scale, softcap)  # [Tq, h, T]
        key_pos = jnp.arange(k.shape[0])
        lim = jnp.minimum(q_pos + 1, seq_len)
        valid = key_pos[None, :] < lim[:, None]
        if windowed:
            valid &= jnp.where(
                w > 0, key_pos[None, :] > q_pos[:, None] - w, True
            )
        scores = jnp.where(valid[:, None, :], scores, NEG_INF)
        if sinks is None:
            weights = jax.nn.softmax(scores, axis=-1)
        else:
            weights = _sink_softmax(scores, sinks.astype(jnp.float32))
        out = _gqa_values(weights, v)               # [Tq, h, d] f32
        return jnp.where(member[:, None, None], out, 0.0)

    w_arg = (
        windows if windowed
        else jnp.zeros(block_tables.shape[0], jnp.int32)
    )
    outs = jax.vmap(one)(block_tables, q_starts, q_lens, seq_lens, w_arg)
    # segments are disjoint, so summing the per-row masked outputs packs them
    return jnp.sum(outs, axis=0).astype(q.dtype)


def paged_extend_attention(
    q: jax.Array,             # [B, S_new, h, d] candidate-token queries
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks] int32
    start_pos: jax.Array,     # [B] absolute position of each row's q[0]
    total_lens: jax.Array,    # [B] context length incl. the S_new candidates
    window: Optional[int] = None,
    sinks: Optional[jax.Array] = None,
    softcap: Optional[float] = None,
) -> jax.Array:
    """Batched paged prefix-extend: every row attends its S_new new tokens
    causally over its OWN pages (which must already contain the new tokens'
    KV). The verify pass of speculative decoding
    (docs/speculative_decoding.md) is this shape; Pallas engines fold it
    into the unified ragged kernel as ``query_len = k+1`` rows, while
    pure-JAX engines keep this op as their fallback split dispatch (the
    unified TWIN would score the whole packed buffer per row — O(B^2)
    verify FLOPs). KERNEL-SPLIT flags any new engine call site.

    vmap of gather_kv + extend_attention: pure JAX, any head layout the
    single-sequence ops accept (GQA, MQA/MLA-latent), window/sinks
    supported. Windowed rows gather only the trailing blocks covering
    [start - window + 1, start + S_new) — the queries all sit at the tail,
    so like paged_decode_attention a 128-token window over a long context
    reads ~window + S_new keys, not the whole table."""
    S_new = q.shape[1]
    bs = k_cache.shape[1]
    if window is not None:
        wb = min(
            (window + S_new + bs - 1) // bs + 1, block_tables.shape[1]
        )

    def one(qb, table, start, tlen):
        positions = start + jnp.arange(S_new)
        if window is None:
            k_ctx, v_ctx = gather_kv(k_cache, v_cache, table)
            return extend_attention(
                qb, k_ctx, v_ctx, positions, tlen, sinks=sinks,
                softcap=softcap,
            )
        nblocks = jnp.maximum((tlen + bs - 1) // bs, 1)
        first = jnp.maximum(nblocks - wb, 0)
        sub = table[jnp.clip(first + jnp.arange(wb), 0, table.shape[0] - 1)]
        k_ctx, v_ctx = gather_kv(k_cache, v_cache, sub)   # [wb*bs, kvh, d]
        # extend_attention masks by ABSOLUTE key position; the gathered
        # window starts at first*bs, so shift the query positions and the
        # valid length into the gathered frame
        off = first * bs
        return extend_attention(
            qb, k_ctx, v_ctx, positions - off, tlen - off,
            window=window, sinks=sinks, softcap=softcap,
        )

    return jax.vmap(one)(q, block_tables, start_pos, total_lens)
