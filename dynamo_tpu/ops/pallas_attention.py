"""Pallas TPU kernel: ragged paged decode attention.

Drop-in replacement for ``ops.attention.paged_decode_attention`` on the decode
hot path. The pure-JAX formulation gathers every sequence's full (padded) page
table out of HBM each step; this kernel instead walks each sequence's *actual*
pages with explicit HBM->VMEM DMAs, double-buffered so page fetch overlaps the
flash-attention compute. HBM traffic becomes proportional to the ragged sum of
true context lengths rather than B * max_blocks.

This is the TPU analog of what the reference delegates to vLLM/FlashInfer
paged-attention CUDA kernels (engine-internal; see SURVEY.md §2.5) — written
from scratch against the paged layout ``[num_blocks, block_size, kv_heads,
head_dim]`` shared with ops/attention.py and the KVBM transfer plane.

Grid: one program per sequence. Scalar-prefetched block tables + sequence
lengths (SMEM) drive the page DMAs; online-softmax (flash) accumulation over
chunks of pages keeps VMEM usage constant in context length.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import shard_map
from .quant import QuantizedKV, is_quantized

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch (SMEM)
    tables_ref,     # [B * max_blocks] int32 flattened block tables
    lens_ref,       # [B] int32 context lengths (incl. current token)
    # inputs
    q_ref,          # VMEM [1, h, d] this sequence's query
    k_hbm,          # ANY/HBM [num_blocks, bs, kvh, d] (model dtype or int8)
    v_hbm,          # ANY/HBM [num_blocks, bs, kvh, d]
    # quantized=True only: ks_hbm/vs_hbm ANY/HBM [num_blocks, kvh] f32 scales
    # outputs
    # o_ref         VMEM [1, h, d]
    # scratch
    # k_buf/v_buf   VMEM [2, CP, bs, kvh, d] double-buffered page chunks
    # quantized=True only: ks_buf/vs_buf VMEM [2, CP, kvh] f32 scale rows
    # sem           DMA sems [2, 2, CP] (k/v, slot, page)
    # quantized=True only: ssem DMA sems [2, 2, CP] for the scale rows
    *rest,
    max_blocks: int,
    chunk_pages: int,
    quantized: bool,
):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sem,
         ssem) = rest
    else:
        o_ref, k_buf, v_buf, sem = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = ssem = None
    b = pl.program_id(0)
    bs, kvh, d = k_hbm.shape[1], k_hbm.shape[2], k_hbm.shape[3]
    h = q_ref.shape[1]
    g = h // kvh
    CP = chunk_pages
    T = CP * bs

    seq_len = lens_ref[b]
    num_pages = pl.cdiv(seq_len, bs)
    num_chunks = pl.cdiv(num_pages, CP)

    def page_dma(kind, c, j, slot):
        """DMA descriptor for page j of chunk c into buffer slot."""
        idx = tables_ref[b * max_blocks + c * CP + j]
        src = k_hbm if kind == 0 else v_hbm
        dst = k_buf if kind == 0 else v_buf
        return pltpu.make_async_copy(
            src.at[idx], dst.at[slot, j], sem.at[kind, slot, j]
        )

    def scale_dma(kind, c, j, slot):
        """Scale-row DMA for page j: rides the same prefetched table index
        the page DMA uses — [kvh] f32 per page, ~1000x smaller than the
        payload it describes. NOTE (hardware): this slice's minor dim is
        kvh, not 128-aligned; CPU tier-1 only exercises interpret mode, so
        the first real-TPU int8 run must confirm Mosaic accepts the copy
        (fallback if not: use_pallas=False or pad scales to [nb, kvh, 128]
        sublane-major)."""
        idx = tables_ref[b * max_blocks + c * CP + j]
        src = ks_hbm if kind == 0 else vs_hbm
        dst = ks_buf if kind == 0 else vs_buf
        return pltpu.make_async_copy(
            src.at[idx], dst.at[slot, j], ssem.at[kind, slot, j]
        )

    def start_chunk(c, slot):
        for j in range(CP):  # static unroll; guard ragged tail
            @pl.when(c * CP + j < num_pages)
            def _():
                page_dma(0, c, j, slot).start()
                page_dma(1, c, j, slot).start()
                if quantized:
                    scale_dma(0, c, j, slot).start()
                    scale_dma(1, c, j, slot).start()

    def wait_chunk(c, slot):
        for j in range(CP):
            @pl.when(c * CP + j < num_pages)
            def _():
                page_dma(0, c, j, slot).wait()
                page_dma(1, c, j, slot).wait()
                if quantized:
                    scale_dma(0, c, j, slot).wait()
                    scale_dma(1, c, j, slot).wait()

    start_chunk(0, 0)

    scale = 1.0 / (d ** 0.5)
    qf = q_ref[0].astype(jnp.float32) * scale  # [h, d]

    def body(c, carry):
        m_prev, l_prev, acc_prev = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < num_chunks)
        def _():
            start_chunk(c + 1, jax.lax.rem(c + 1, 2))

        wait_chunk(c, slot)

        if quantized:
            # dequantize in-register: int8 page chunks -> f32 scaled by the
            # per-(page, kv-head) rows that just DMA'd in alongside them.
            # HBM traffic for the K/V bytes themselves is halved vs bf16.
            k = (
                k_buf[slot].astype(jnp.float32)
                * ks_buf[slot][:, None, :, None]
            ).reshape(T, kvh, d)
            v = (
                v_buf[slot].astype(jnp.float32)
                * vs_buf[slot][:, None, :, None]
            ).reshape(T, kvh, d)
        else:
            k = k_buf[slot].reshape(T, kvh, d).astype(jnp.float32)
            v = v_buf[slot].reshape(T, kvh, d).astype(jnp.float32)
        # rows past seq_len were never DMA'd (garbage / NaN): scores are
        # masked below, but V must be zeroed too — 0-weight * NaN = NaN in
        # the PV matmul otherwise
        row_pos = c * T + jax.lax.broadcasted_iota(jnp.int32, (T, 1, 1), 0)
        v = jnp.where(row_pos < seq_len, v, 0.0)

        # scores [h, T]: per-kv-head MXU matmuls (GQA grouping: q heads
        # [i*g, (i+1)*g) attend kv head i, matching attention._gqa_scores)
        parts = []
        for i in range(kvh):
            s_i = jax.lax.dot_general(
                qf[i * g:(i + 1) * g], k[:, i, :],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [g, T]
            parts.append(s_i)
        s = jnp.concatenate(parts, axis=0) if kvh > 1 else parts[0]

        key_pos = c * T + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        s = jnp.where(key_pos < seq_len, s, NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)            # [h, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # [h, T]
        alpha = jnp.exp(m_prev - m_new)                       # [h, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

        outs = []
        for i in range(kvh):
            o_i = jax.lax.dot_general(
                p[i * g:(i + 1) * g], v[:, i, :],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [g, d]
            outs.append(o_i)
        pv = jnp.concatenate(outs, axis=0) if kvh > 1 else outs[0]
        acc_new = alpha * acc_prev + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((h, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((h, 1), jnp.float32)
    a0 = jnp.zeros((h, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, a0))

    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("chunk_tokens", "interpret")
)
def paged_decode_attention(
    q: jax.Array,             # [B, h, d]
    k_cache: jax.Array,       # [num_blocks, bs, kvh, d]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks] int32
    seq_lens: jax.Array,      # [B] int32
    *,
    chunk_tokens: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Ragged paged decode attention (Pallas). Same semantics as
    ``ops.attention.paged_decode_attention``. ``k_cache``/``v_cache`` may be
    ``QuantizedKV`` (int8 payload + per-block scales): the kernel DMAs the
    int8 pages plus their scale rows and dequantizes in-register, so the
    per-page HBM bytes halve vs bf16."""
    B, h, d = q.shape
    _, bs, kvh, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    chunk_pages = max(1, chunk_tokens // bs)
    quantized = is_quantized(k_cache)

    kernel = functools.partial(
        _decode_kernel, max_blocks=max_blocks, chunk_pages=chunk_pages,
        quantized=quantized,
    )
    cache_specs = [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch = [
        pltpu.VMEM((2, chunk_pages, bs, kvh, d), k_cache.dtype),
        pltpu.VMEM((2, chunk_pages, bs, kvh, d), v_cache.dtype),
    ]
    if quantized:
        cache_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # k scales [num_blocks, kvh]
            pl.BlockSpec(memory_space=pl.ANY),  # v scales
        ]
        scratch += [
            pltpu.VMEM((2, chunk_pages, kvh), jnp.float32),
            pltpu.VMEM((2, chunk_pages, kvh), jnp.float32),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((2, 2, chunk_pages)))
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((2, 2, chunk_pages)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[pl.BlockSpec((1, h, d), lambda b, *_: (b, 0, 0))]
        + cache_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda b, *_: (b, 0, 0)),
        scratch_shapes=scratch,
    )
    cache_args = (
        (k_cache.data, v_cache.data, k_cache.scale, v_cache.scale)
        if quantized else (k_cache, v_cache)
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        interpret=interpret,
    )(
        block_tables.reshape(-1).astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        q,
        *cache_args,
    )


def sharded_paged_decode_attention(
    mesh: Mesh,
    tp_axis: str,
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    **kw,
) -> jax.Array:
    """TP-sharded wrapper: attention is head-wise independent, so each TP
    shard runs the kernel on its own heads (q sharded on h, caches on kvh —
    parallel/mesh.kv_cache_spec). Uses shard_map because XLA's GSPMD cannot
    partition a custom call on its own."""
    if mesh.shape[tp_axis] == 1:
        return paged_decode_attention(
            q, k_cache, v_cache, block_tables, seq_lens, **kw
        )
    cache_spec = P(None, None, tp_axis, None)
    if is_quantized(k_cache):
        # spec tree mirrors the QuantizedKV pytree: payload shards on
        # kv_heads like the float cache, scale rows on their kv-head dim
        cache_spec = QuantizedKV(cache_spec, P(None, tp_axis))
    fn = shard_map(
        functools.partial(paged_decode_attention, **kw),
        mesh=mesh,
        in_specs=(
            P(None, tp_axis, None),
            cache_spec,
            cache_spec,
            P(None, None),
            P(None),
        ),
        out_specs=P(None, tp_axis, None),
        check_vma=False,
    )
    return fn(q, k_cache, v_cache, block_tables, seq_lens)
