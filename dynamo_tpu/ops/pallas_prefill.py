"""Pallas TPU kernel: flash prefix-extend attention for chunked prefill.

Drop-in replacement for ``ops.attention.extend_attention`` on the prefill hot
path. The pure-JAX formulation materializes the full [S, h, T] score tensor
(67 MB of f32 per head at an 8k context) and re-reads it for softmax and PV;
this kernel streams KV tiles through VMEM with online-softmax accumulation —
O(tile) VMEM at any context length, the standard flash-attention recipe
tiled for the MXU.

The TPU analog of the prefill-side flash kernels the reference's engines use
internally (vLLM/TRT-LLM chunked-prefill attention; SURVEY §2.5). Shares the
contiguous gathered-KV layout of ops/attention.py: the engine gathers pages
once per chunk, and this kernel replaces only the attention math.

Grid: (kv_heads, q_tiles, kv_tiles) — the LAST dim iterates sequentially on
TPU, so the online-softmax state (m/l/acc) lives in VMEM scratch carried
across kv steps; K/V arrive one (kv_tile, d) block at a time via BlockSpecs.
Tiles entirely past this q-tile's attention limit skip their matmuls
(``pl.when``). Causality is absolute-position based (``q_positions`` vs key
index), so the same kernel serves first-chunk prefill, chunked continuation
against a cached prefix, and prefix-cache-reuse suffixes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import shard_map

NEG_INF = -1e30

# default tile sizes; the engine's eligibility guard imports these so the
# two never drift (engine/engine.py prefill attend)
Q_TILE = 128
KV_TILE = 256


def _prefill_kernel(
    start_ref,   # SMEM [1] int32 absolute position of q row 0 (scalar prefetch)
    tlen_ref,    # SMEM [1] int32 valid context length (scalar prefetch)
    q_ref,       # VMEM [1, TQ, g, d] this (kv_head, q_tile)'s queries
    k_ref,       # VMEM [1, KT, d] one KV tile of this kv_head's context
    v_ref,       # VMEM [1, KT, d]
    # quantized=True only: ks_ref/vs_ref VMEM [1, KT, 1] f32 per-position
    # scales (per-block scales broadcast at gather time)
    *rest,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    # Mosaic only loads SCALARS from SMEM, so q positions can't arrive as a
    # prefetched vector; they're derived from start_ref + the row iota
    # instead (engine chunks are contiguous — _chunk_arrays). Both the
    # per-row mask and the tile-skip bound are then scalar-rooted.
    qt = pl.program_id(1)
    c = pl.program_id(2)
    n_kv = pl.num_programs(2)
    _, TQ, g, d = q_ref.shape
    KT = k_ref.shape[1]
    start = start_ref[0]
    tlen = tlen_ref[0]

    @pl.when(c == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # per-row attention limit: keys at index < min(q_pos+1, total_len);
    # rows past the real chunk clamp to tlen (their output is discarded)
    tile_hi = jnp.minimum(start + (qt + 1) * TQ, tlen)         # scalar

    @pl.when(c * KT < tile_hi)
    def _tile():
        scale = 1.0 / (d ** 0.5)
        q2 = (q_ref[0].astype(jnp.float32) * scale).reshape(TQ * g, d)
        # row index per flattened (q, g) pair, built directly in the
        # [TQ*g, 1] layout: reshaping a (TQ, g) iota would shape-cast across
        # the lane dim, which Mosaic rejects (infer-vector-layout error on
        # real TPU); iota//g keeps the lane dim fixed at 1 throughout
        row = jax.lax.broadcasted_iota(jnp.int32, (TQ * g, 1), 0) // g
        pos = start + qt * TQ + row
        lim2 = jnp.minimum(pos + 1, tlen)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # dequantize in-register: [KT, d] int8 tile * [KT, 1] scale
            # column (lane-dim broadcast) — the HBM->VMEM tile stream stays
            # int8, so prefill context reads halve vs bf16 too
            k = k * ks_ref[0]
            v = v * vs_ref[0]
        s = jax.lax.dot_general(
            q2, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                      # [TQ*g, KT]
        key_pos = c * KT + jax.lax.broadcasted_iota(jnp.int32, (1, KT), 1)
        s = jnp.where(key_pos < lim2, s, NEG_INF)

        m_prev, l_prev, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = alpha * acc + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(c == n_kv - 1)
    def _emit():
        out = acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = out.reshape(TQ, g, d).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("q_tile", "kv_tile", "interpret")
)
def flash_extend_attention(
    q: jax.Array,            # [S, h, d] new-chunk queries
    k_ctx: jax.Array,        # [T, kvh, d] gathered context (padded)
    v_ctx: jax.Array,
    q_positions: jax.Array,  # [S] absolute positions
    total_len: jax.Array,    # scalar valid context length
    *,
    k_scales: jax.Array = None,  # [T, kvh] f32: k_ctx/v_ctx are int8 pages
    v_scales: jax.Array = None,  # (ops.attention.gather_kv_quant output)
    q_tile: int = Q_TILE,
    kv_tile: int = KV_TILE,
    interpret: bool = False,
) -> jax.Array:
    """Same semantics as ``ops.attention.extend_attention`` for CONTIGUOUS
    q_positions (the engine's chunks are: row i sits at q_positions[0]+i;
    padded tail rows may carry arbitrary positions — their output is
    discarded by the caller). S and T must be multiples of the tile sizes
    (the engine's bucketed chunks are).

    With ``k_scales``/``v_scales`` the context is int8 (quantized paged
    cache) and the kernel dequantizes each tile in-register."""
    S, h, d = q.shape
    T, kvh, _ = k_ctx.shape
    g = h // kvh
    quantized = k_scales is not None
    if S % q_tile or T % kv_tile:
        raise ValueError(
            f"S={S} / T={T} not multiples of tiles ({q_tile}, {kv_tile})"
        )
    nq = S // q_tile
    nkv = T // kv_tile

    # [S, h, d] -> [kvh, S, g, d]: each kv head's q group contiguous
    qg = q.reshape(S, kvh, g, d).transpose(1, 0, 2, 3)
    kg = k_ctx.transpose(1, 0, 2)  # [kvh, T, d]
    vg = v_ctx.transpose(1, 0, 2)

    in_specs = [
        pl.BlockSpec((1, q_tile, g, d), lambda kh, qt, c, *_: (kh, qt, 0, 0)),
        pl.BlockSpec((1, kv_tile, d), lambda kh, qt, c, *_: (kh, c, 0)),
        pl.BlockSpec((1, kv_tile, d), lambda kh, qt, c, *_: (kh, c, 0)),
    ]
    args = [qg, kg, vg]
    if quantized:
        # [T, kvh] -> [kvh, T, 1]: tiles broadcast over the lane (d) dim
        in_specs += [
            pl.BlockSpec((1, kv_tile, 1), lambda kh, qt, c, *_: (kh, c, 0)),
            pl.BlockSpec((1, kv_tile, 1), lambda kh, qt, c, *_: (kh, c, 0)),
        ]
        args += [
            k_scales.astype(jnp.float32).T[:, :, None],
            v_scales.astype(jnp.float32).T[:, :, None],
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(kvh, nq, nkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, q_tile, g, d), lambda kh, qt, c, *_: (kh, qt, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((q_tile * g, 1), jnp.float32),
            pltpu.VMEM((q_tile * g, 1), jnp.float32),
            pltpu.VMEM((q_tile * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, S, g, d), q.dtype),
        interpret=interpret,
    )(
        q_positions[:1].astype(jnp.int32),  # chunk start (row 0's position)
        jnp.asarray(total_len, jnp.int32).reshape(1),
        *args,
    )
    # [kvh, S, g, d] -> [S, h, d]
    return out.transpose(1, 0, 2, 3).reshape(S, h, d)


def sharded_flash_extend_attention(
    mesh: Mesh,
    tp_axis: str,
    q: jax.Array,
    k_ctx: jax.Array,
    v_ctx: jax.Array,
    q_positions: jax.Array,
    total_len: jax.Array,
    k_scales: jax.Array = None,
    v_scales: jax.Array = None,
    **kw,
) -> jax.Array:
    """TP-sharded wrapper: extend attention is head-wise independent, so each
    TP shard runs the kernel on its own heads (q sharded on h, context on
    kvh). shard_map because GSPMD cannot partition a custom call — the same
    treatment as pallas_attention.sharded_paged_decode_attention."""
    if mesh.shape[tp_axis] == 1:
        return flash_extend_attention(
            q, k_ctx, v_ctx, q_positions, total_len,
            k_scales=k_scales, v_scales=v_scales, **kw
        )
    in_specs = [
        P(None, tp_axis, None),
        P(None, tp_axis, None),
        P(None, tp_axis, None),
        P(None),
        P(),
    ]
    args = [q, k_ctx, v_ctx, q_positions, total_len]
    if k_scales is not None:
        # int8 context: scale rows shard on their kv-head dim with the pages
        in_specs += [P(None, tp_axis), P(None, tp_axis)]
        args += [k_scales, v_scales]

    def body(q_, k_, v_, pos_, tlen_, *scales_):
        ks_, vs_ = scales_ if scales_ else (None, None)
        return flash_extend_attention(
            q_, k_, v_, pos_, tlen_, k_scales=ks_, v_scales=vs_, **kw
        )

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=P(None, tp_axis, None),
        check_vma=False,
    )
    return fn(*args)
