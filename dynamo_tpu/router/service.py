"""RouterService: KV-aware routing served over the request plane.

Analog of the reference's standalone router (components/src/dynamo/router/
__main__.py:4-13,30-60 — a KvPushRouter exposed as its own component so
N frontends / prefill orchestrators can share one routing brain). The service
watches the target component's instance registry for candidates, runs a full
KvRouter (indexer + scheduler, optionally replica-synced with other router
instances), and answers:

    {"op": "route", "request_id": ..., "token_ids": [...]}
        -> {"worker_id", "dp_rank", "overlap_blocks", "cached_tokens"}
    {"op": "free", "request_id": ...}            -> {"ok": true}
    {"op": "state"}                              -> introspection snapshot
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional

from ..kv_router import KvRouter, KvRouterConfig, WorkerWithDpRank
from ..runtime.component import Client, RouterMode
from ..runtime.distributed import DistributedRuntime
from ..runtime.engine import Context
from ..runtime.logging import get_logger

log = get_logger("router.service")


class RouterService:
    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str = "dynamo",
        component: str = "backend",
        endpoint: str = "generate",
        block_size: int = 16,
        config: Optional[KvRouterConfig] = None,
        recorder=None,
    ):
        self.runtime = runtime
        self.recorder = recorder
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.block_size = block_size
        self.config = config or KvRouterConfig()
        self.client: Optional[Client] = None
        self.router: Optional[KvRouter] = None
        self.served = None
        self._known_worker_ids: set = set()

    async def start(self) -> "RouterService":
        target = (
            self.runtime.namespace(self.namespace)
            .component(self.component)
            .endpoint(self.endpoint)
        )
        self.client = await target.client(RouterMode.ROUND_ROBIN)
        self.router = await KvRouter(
            self.runtime.event_plane,
            self.namespace,
            self.component,
            block_size=self.block_size,
            config=self.config,
            recorder=self.recorder,
            # standalone router: its overlap hits land on ITS /metrics
            metrics=getattr(self.runtime, "metrics", None),
        ).start()
        ep = (
            self.runtime.namespace(self.namespace)
            .component(f"{self.component}-router")
            .endpoint("route")
        )
        self.served = await ep.serve(
            self.handle, metadata={"router_id": self.router.router_id}
        )
        return self

    def _candidates(self) -> List[WorkerWithDpRank]:
        assert self.client is not None
        cands: List[WorkerWithDpRank] = []
        for iid, inst in self.client.instances.items():
            dp = int(inst.metadata.get("data_parallel_size", 1) or 1)
            for r in range(dp):
                cands.append(WorkerWithDpRank(iid, r))
        return cands

    def _prune_dead_workers(self) -> None:
        assert self.router is not None and self.client is not None
        live = set(self.client.instances)
        # sweep the router's registered universe, not a known-set delta: a
        # stale metrics event auto-registers workers in the scheduler
        # (update_metrics), so a removed worker can be resurrected after
        # its one-shot delta removal and must be swept out again
        for w in self.router.scheduler.known_workers():
            if w.worker_id not in live:
                self.router.remove_worker_id(w.worker_id)
        self._known_worker_ids = live

    async def handle(self, request: Any, context: Context) -> AsyncIterator[Any]:
        op = request.get("op", "route")
        if op == "route":
            self._prune_dead_workers()
            cands = self._candidates()
            if not cands:
                yield {"error": "no workers available"}
                return
            decision = self.router.schedule_tokens(
                list(request["token_ids"]), cands,
                request_id=request.get("request_id"),
            )
            yield {
                "worker_id": decision.worker.worker_id,
                "dp_rank": decision.worker.dp_rank,
                "overlap_blocks": decision.overlap_blocks,
                "cached_tokens": decision.overlap_blocks * self.block_size,
            }
        elif op == "free":
            self.router.complete(request["request_id"])
            yield {"ok": True}
        elif op == "state":
            yield {
                "router_id": self.router.router_id,
                "tree_blocks": len(self.router.indexer.tree),
                "workers": [w.to_obj() for w in self.router.indexer.tree.workers()],
                "synced_from_peer": self.router.synced_from_peer,
            }
        else:
            yield {"error": f"unknown op {op!r}"}

    async def stop(self) -> None:
        if self.served is not None:
            await self.served.stop()
        if self.router is not None:
            await self.router.stop()
        if self.client is not None:
            await self.client.stop()
