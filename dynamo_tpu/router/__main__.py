"""python -m dynamo_tpu.router — standalone KV-router service.

Analog of the reference's `python -m dynamo.router`
(components/src/dynamo/router/__main__.py): exposes KV-aware worker selection
for a component's worker set as its own endpoint, so prefill orchestrators
and multiple frontends can share one routing brain. Run several with
--replica-sync and their load/prefix views stay consistent.
"""

import argparse
import asyncio
import signal

from dynamo_tpu.kv_router import KvRouterConfig
from dynamo_tpu.router.service import RouterService
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.router")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--no-kv-events", action="store_true",
                   help="use the ApproxKvIndexer instead of worker KV events")
    p.add_argument("--replica-sync", action="store_true",
                   help="sync decisions + state with other router instances")
    p.add_argument("--record-events", default=None, metavar="PATH",
                   help="record the ingested KV-event stream to a JSONL file "
                        "(runtime/recorder.py; replayable with Recorder.replay)")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()
    recorder = None
    if args.record_events:
        from dynamo_tpu.runtime.recorder import Recorder

        recorder = await Recorder(args.record_events).start()
    service = await RouterService(
        runtime,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint,
        block_size=args.block_size,
        config=KvRouterConfig(
            overlap_score_weight=args.overlap_score_weight,
            router_temperature=args.router_temperature,
            use_kv_events=not args.no_kv_events,
            replica_sync=args.replica_sync,
        ),
        recorder=recorder,
    ).start()
    print(f"ROUTER_READY {service.router.router_id}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await service.stop()
    if recorder is not None:
        await recorder.stop()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
