"""Standalone KV-router service (analog of the reference's
components/src/dynamo/router: a routing endpoint any client can call for a
worker set it does not own — used for prefill pools and shared frontends)."""

from .service import RouterService

__all__ = ["RouterService"]
