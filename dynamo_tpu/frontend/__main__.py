"""python -m dynamo_tpu.frontend — OpenAI HTTP frontend + model watcher.

Analog of the reference's `python -m dynamo.frontend`
(components/src/dynamo/frontend/main.py): one process running the OpenAI
HTTP server, the MDC watcher, the preprocessor and the (KV) router.
"""

import argparse
import asyncio
import signal

from dynamo_tpu.kv_router import KvRouterConfig
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.request_template import RequestTemplate
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig, init_logging
from dynamo_tpu.runtime.config import (
    ENV_BUSY_THRESHOLD,
    ENV_HTTP_PORT,
    ENV_NAMESPACE,
    env_int,
    env_str,
)


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.frontend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=env_int(ENV_HTTP_PORT, 8000))
    p.add_argument(
        "--router-mode", choices=["round-robin", "random", "kv"], default="round-robin"
    )
    p.add_argument("--namespace", default=env_str(ENV_NAMESPACE, "dynamo"))
    p.add_argument("--store", default=None, help="mem|file (default from DTPU_STORE)")
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None, help="zmq|inproc")
    p.add_argument("--busy-threshold", type=int,
                   default=(env_int(ENV_BUSY_THRESHOLD, 0) or None))
    p.add_argument("--grpc-port", type=int, default=-1,
                   help="KServe v2 gRPC frontend port (0 = ephemeral, -1 = off)")
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--no-kv-events", action="store_true")
    p.add_argument("--tls-cert-path", default=None,
                   help="serve HTTPS with this PEM cert (requires --tls-key-path)")
    p.add_argument("--tls-key-path", default=None)
    p.add_argument("--request-template", default=None,
                   help="JSON file with default model/temperature/"
                        "max_completion_tokens applied to requests that "
                        "omit them")
    args = p.parse_args()
    if bool(args.tls_cert_path) != bool(args.tls_key_path):
        p.error("--tls-cert-path and --tls-key-path must be given together")
    return args


async def main() -> None:
    args = parse_args()
    init_logging()
    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()
    manager = ModelManager()
    kv_cfg = KvRouterConfig(
        overlap_score_weight=args.kv_overlap_score_weight,
        router_temperature=args.router_temperature,
        use_kv_events=not args.no_kv_events,
    )
    watcher = await ModelWatcher(
        runtime, manager, RouterMode(args.router_mode), kv_cfg
    ).start()
    # per-request stats onto the event plane: the planner's demand +
    # correction-factor feed (planner/metrics_source.py)
    from dynamo_tpu.planner.metrics_source import FrontendStatsPublisher

    stats = FrontendStatsPublisher(runtime.event_plane, args.namespace)
    service = HttpService(
        manager, runtime.metrics, busy_threshold=args.busy_threshold,
        host=args.host, port=args.port, stats_hook=stats.on_request,
        tls_cert=args.tls_cert_path, tls_key=args.tls_key_path,
        request_template=(
            RequestTemplate.load(args.request_template)
            if args.request_template else None
        ),
    )
    await service.start()
    grpc_service = None
    if args.grpc_port >= 0:
        from dynamo_tpu.llm.grpc import KserveGrpcService

        grpc_service = KserveGrpcService(manager, host=args.host, port=args.grpc_port)
        await grpc_service.start()
        print(f"KSERVE_GRPC_READY {grpc_service.port}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if grpc_service is not None:
        await grpc_service.stop()
    await service.stop()
    await watcher.stop()
    await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
