"""Pipeline-parallel SERVING forward: paged-KV prefill + decode over a pp mesh.

Where the reference passes ``pipeline_parallel_size`` into its serving
engines' NCCL groups (components/src/dynamo/trtllm/engine.py:118,
vllm/args.py), this framework owns the model, so serving PP is a JAX
transform built from the same pieces as the training pipeline
(parallel/pipeline.py): layer params stacked [L, ...] and sharded over the
``pp`` mesh axis, a ``shard_map`` wavefront moving activations rank->rank via
``lax.ppermute``, megatron TP (column/row shards + psum) inside each stage.

What differs from training: each stage owns its layers' slice of the paged
KV cache (stacked [L, num_blocks, bs, kvh, d], L sharded over pp, kvh over
tp) and runs cache-aware attention — ``write_prefill_kv``/``gather_kv``/
``extend_attention`` for prefill chunks, ``write_decode_kv``/
``paged_decode_attention`` for decode — on its local shards.

Schedules: prefill (one sequence per dispatch) rides a one-microbatch
pp-tick wavefront; DECODE runs a generalized (M + pp - 1)-tick schedule
where rank s owns microbatch t - s on tick t, and INVALID ticks skip their
stage compute entirely via lax.cond (safe: a TP group shares its pp rank,
so the stage psum stays collective-uniform). Decode at serving batch sizes
is weight-bandwidth bound — a stage tick costs ~one read of the stage's
weights regardless of rows — so the default is M = 1 (pp ticks, one real
stage execution per rank per step); DTPU_PP_MICROBATCHES=<M> opts into
GPipe bubble amortization for compute-bound regimes (large B), where work
drops from pp x B rows to (M + pp - 1) x B/M.
profiler/fleet_bench.pp_bubble_bench measures both schedules. KV commits
are additionally masked to scratch block 0 on invalid ticks (block 0 is
never allocated). The final stage's outputs are psum-broadcast so sampling
outside the shard_map sees replicated values.

The engine plugs these in as drop-in forwards (engine/engine.py
_build_programs, cfg.pp > 1): the surrounding program — sampling, penalties,
logprobs, the decode_multi scan, donation, chained horizons — is unchanged,
with the stacked caches living as 1-element k_caches/v_caches lists.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..ops import attention as att
from . import mesh as meshlib
from .mesh import AXIS_TP
from .pipeline import (
    AXIS_PP,
    _rms,
    make_pp_mesh,
    place_stacked,
    stack_params,
    stacked_param_specs,
)

__all__ = [
    "make_pp_mesh", "place_serving_params", "init_pp_caches",
    "pp_cache_spec", "make_pp_prefill_forward", "make_pp_decode_forward",
]


def pp_cache_spec() -> P:
    """Stacked paged KV [L, num_blocks, bs, kvh, d]: layers over pp, kv
    heads over tp."""
    return P(AXIS_PP, None, None, AXIS_TP, None)


def place_serving_params(mesh: Mesh, params) -> dict:
    """Param pytree (list-of-layers) -> stacked + sharded for serving PP."""
    host = jax.tree.map(np.asarray, params)  # collective-put friendly
    return place_stacked(mesh, stack_params(host))


def init_pp_caches(
    mesh: Mesh, num_layers: int, num_blocks: int, block_size: int,
    num_kv_heads: int, head_dim: int, dtype,
) -> Tuple[jax.Array, jax.Array]:
    shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
    sharding = NamedSharding(mesh, pp_cache_spec())
    k = jax.device_put(np.zeros(shape, dtype), sharding)
    v = jax.device_put(np.zeros(shape, dtype), sharding)
    return k, v


def _check_cfg(mcfg: llama.LlamaConfig, pp: int, tp: int) -> None:
    # registry-level family gate (VERDICT r5 directive)
    from ..models import registry

    registry.check_pp_supported(mcfg)
    if mcfg.num_layers % pp:
        raise ValueError(f"num_layers {mcfg.num_layers} not divisible by pp={pp}")
    if mcfg.num_kv_heads % tp or mcfg.num_heads % tp:
        raise ValueError(f"heads not divisible by tp={tp}")


def _stage_scan(serve_layer, lp_local, k_local, v_local, x, attend_one):
    """Apply this rank's layer slice: scan over local layers, threading the
    hidden state and updating each layer's cache slice.

    attend_one(q, k_new, v_new, kc, vc) -> (out, kc', vc') runs this
    sub-problem's cache-aware attention on LOCAL tp shards.
    x: [S, H]; lp_local: dict of [L/pp, ...]; k/v_local: [L/pp, nb, bs, kvl, d].
    """

    def body(h, per_layer):
        lp, kc, vc = per_layer
        out, kc, vc = serve_layer(lp, h, kc, vc, attend_one)
        return out, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(body, x, (lp_local, k_local, v_local))
    return x, k_new, v_new


def _make_serve_layer(mcfg: llama.LlamaConfig, tp: int, cos, sin):
    """Returns serve_layer(lp, x, kc, vc, attend_one) for [S, H] inputs.
    Covers the full dense family incl. Qwen2-style qkv_bias and Qwen3-style
    per-head q/k RMSNorm (models/llama.py:195-203 is the non-pp original)."""
    d = mcfg.head_dim
    hl = mcfg.num_heads // tp
    kvl = mcfg.num_kv_heads // tp
    qkv_bias = getattr(mcfg, "qkv_bias", False)
    qk_norm = getattr(mcfg, "qk_norm", False)

    def serve_layer(lp, x, kc, vc, attend_one):
        h = _rms(x, lp["attn_norm"], mcfg.rms_norm_eps)
        q, k, v = h @ lp["wq"], h @ lp["wk"], h @ lp["wv"]
        if qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(-1, hl, d)
        k = k.reshape(-1, kvl, d)
        v = v.reshape(-1, kvl, d)
        if qk_norm:
            q = _rms(q, lp["q_norm"], mcfg.rms_norm_eps)
            k = _rms(k, lp["k_norm"], mcfg.rms_norm_eps)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        o, kc, vc = attend_one(q, k, v, kc, vc)
        o = o.reshape(x.shape[0], hl * d).astype(x.dtype) @ lp["wo"]
        x = x + jax.lax.psum(o, AXIS_TP)
        h = _rms(x, lp["mlp_norm"], mcfg.rms_norm_eps)
        gate = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32)).astype(x.dtype)
        down = (gate * (h @ lp["w_up"])) @ lp["w_down"]
        return x + jax.lax.psum(down, AXIS_TP), kc, vc

    return serve_layer


def _wavefront(pp: int, x, run_stage):
    """M=1 GPipe wavefront: pp ticks, activations hop rank->rank.

    run_stage(inp, valid) -> (out, ...) applies the local stage; ``valid``
    (traced bool) is True on the tick where ``inp`` is this rank's real
    wavefront input — stages mask their KV writes with it. Returns the last
    stage's output, psum-broadcast to every rank."""
    rank = jax.lax.axis_index(AXIS_PP)
    recv = x
    out = x
    state = None
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    for t in range(pp):
        inp = jnp.where(rank == 0, x, recv) if t == 0 else recv
        out, state = run_stage(inp, jnp.equal(rank, t), state)
        recv = jax.lax.ppermute(out, AXIS_PP, perm)
    # rank pp-1's tick-(pp-1) output is the model output; broadcast it
    final = jnp.where(rank == pp - 1, out, jnp.zeros_like(out))
    return jax.lax.psum(final, AXIS_PP), state


def make_pp_prefill_forward(mesh: Mesh, mcfg: llama.LlamaConfig, pp: int, tp: int):
    """fwd(stacked_params, k_stack, v_stack, tokens, positions, block_table,
    new_block_ids, total_len) -> (hidden [S, H] replicated, k', v').

    One prefill chunk of one sequence: each stage writes the chunk's KV into
    its layers' pages and attends over the gathered context."""
    _check_cfg(mcfg, pp, tp)

    def fwd(params, k_stack, v_stack, tokens, positions, block_table,
            new_block_ids, total_len):
        specs = stacked_param_specs(params)
        cache = pp_cache_spec()

        @partial(
            meshlib.shard_map, mesh=mesh,
            in_specs=(specs, cache, cache, P(), P(), P(), P(), P()),
            out_specs=(P(), cache, cache),
            check_vma=False,
        )
        def run(params, k_stack, v_stack, tokens, positions, block_table,
                new_block_ids, total_len):
            cos, sin = llama.rope_cos_sin(
                positions, mcfg.head_dim, mcfg.rope_theta
            )
            cos, sin = cos[:, None, :], sin[:, None, :]
            serve_layer = _make_serve_layer(mcfg, tp, cos, sin)
            x = params["embed"][tokens]

            def run_stage(inp, valid, _state):
                # garbage ticks write to scratch block 0 (never allocated)
                nbi = jnp.where(valid, new_block_ids, jnp.zeros_like(new_block_ids))

                def attend_one(q, k_new, v_new, kc, vc):
                    kc, vc = att.write_prefill_kv(kc, vc, k_new, v_new, nbi)
                    k_ctx, v_ctx = att.gather_kv(kc, vc, block_table)
                    out = att.extend_attention(
                        q, k_ctx, v_ctx, positions, total_len
                    )
                    return out, kc, vc

                nonlocal_k, nonlocal_v = run_stage.caches
                out, k2, v2 = _stage_scan(
                    serve_layer, params["layers"], nonlocal_k, nonlocal_v,
                    inp, attend_one,
                )
                run_stage.caches = (k2, v2)
                return out, None

            run_stage.caches = (k_stack, v_stack)
            hidden, _ = _wavefront(pp, x, run_stage)
            k2, v2 = run_stage.caches
            hidden = _rms(hidden, params["final_norm"], mcfg.rms_norm_eps)
            return hidden, k2, v2

        return run(params, k_stack, v_stack, tokens, positions, block_table,
                   new_block_ids, total_len)

    return fwd


def make_pp_embed_forward(mesh: Mesh, mcfg: llama.LlamaConfig, pp: int, tp: int):
    """fwd(stacked_params, tokens, positions) -> hidden [S, H] replicated.

    Dense causal attention, no KV pages touched — the /v1/embeddings pooled
    forward (embeddings must never pollute the generation cache)."""
    _check_cfg(mcfg, pp, tp)

    def fwd(params, tokens, positions):
        specs = stacked_param_specs(params)

        @partial(
            meshlib.shard_map, mesh=mesh,
            in_specs=(specs, P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        def run(params, tokens, positions):
            cos, sin = llama.rope_cos_sin(
                positions, mcfg.head_dim, mcfg.rope_theta
            )
            cos, sin = cos[:, None, :], sin[:, None, :]
            serve_layer = _make_serve_layer(mcfg, tp, cos, sin)
            x = params["embed"][tokens]

            def attend_one(q, k_new, v_new, kc, vc):
                return att.causal_attention(q, k_new, v_new), kc, vc

            def run_stage(inp, _valid, _state):
                def body(h, lp):
                    out, _kc, _vc = serve_layer(lp, h, 0.0, 0.0, attend_one)
                    return out, None

                out, _ = jax.lax.scan(body, inp, params["layers"])
                return out, None

            hidden, _ = _wavefront(pp, x, run_stage)
            return _rms(hidden, params["final_norm"], mcfg.rms_norm_eps)

        return run(params, tokens, positions)

    return fwd


def make_pp_decode_forward(mesh: Mesh, mcfg: llama.LlamaConfig, pp: int, tp: int):
    """fwd(stacked_params, k_stack, v_stack, tokens [B], positions [B],
    block_tables, seq_lens, write_blocks, write_offsets)
    -> (hidden [B, H] replicated, k', v').

    MICROBATCHED wavefront: the decode batch splits into M = pp microbatches
    (when B divides evenly; M = 1 otherwise) and rank ``s`` processes
    microbatch ``t - s`` on tick ``t`` over ``M + pp - 1`` ticks — every
    stage is busy on the steady-state ticks, so per-step stage work drops
    from pp x B rows (the one-microbatch wavefront's bubble) to
    (M + pp - 1) x B/M rows: ~2x B at M = pp instead of pp x B. Invalid
    (rank, tick) pairs mask their KV writes to scratch block 0 and their
    garbage activations only ever flow into ticks that are also invalid
    (the microbatch index m = t - s is ppermute-invariant)."""
    _check_cfg(mcfg, pp, tp)

    def fwd(params, k_stack, v_stack, tokens, positions, block_tables,
            seq_lens, write_blocks, write_offsets):
        specs = stacked_param_specs(params)
        cache = pp_cache_spec()
        B = tokens.shape[0]
        # Decode at serving batch sizes is WEIGHT-bandwidth bound: a stage
        # tick costs ~one read of the stage's weights regardless of rows, so
        # splitting the batch into M microbatches trades pp ticks for
        # M + pp - 1 ticks of weight reads — a LOSS unless row compute
        # dominates (large B). Default M = 1; DTPU_PP_MICROBATCHES=<M> opts
        # into bubble amortization for compute-bound regimes
        # (fleet_bench.pp_bubble_bench measures both). Invalid ticks skip
        # their stage compute entirely via lax.cond (per-pp-rank branch;
        # the TP group shares the pp rank, so the psum inside the stage
        # stays collective-uniform).
        try:
            want = int(os.environ.get("DTPU_PP_MICROBATCHES", "1").strip())
        except ValueError:
            want = 1
        M = want if (want > 0 and B % want == 0 and B >= want) else 1
        mb = B // M
        # escape hatch: DTPU_PP_COND_SKIP=0 reverts invalid ticks to
        # always-compute-with-masked-writes (no lax.cond around the cache
        # stacks). cond-skip measured 1.5x faster per step on the CPU mesh;
        # whether XLA aliases the conditional's cache outputs (vs copying
        # multi-GB stacks per skip tick) on real TPU is unprofiled — flip
        # this off if a TPU profile shows copy-insertion costs.
        cond_skip = os.environ.get("DTPU_PP_COND_SKIP", "1") != "0"

        @partial(
            meshlib.shard_map, mesh=mesh,
            in_specs=(specs, cache, cache, P(), P(), P(), P(), P(), P()),
            out_specs=(P(), cache, cache),
            check_vma=False,
        )
        def run(params, k_stack, v_stack, tokens, positions, block_tables,
                seq_lens, write_blocks, write_offsets):
            rank = jax.lax.axis_index(AXIS_PP)
            # per-microbatch views [M, mb, ...]
            toks_mb = tokens.reshape(M, mb)
            pos_mb = positions.reshape(M, mb)
            bt_mb = block_tables.reshape(M, mb, -1)
            sl_mb = seq_lens.reshape(M, mb)
            wb_mb = write_blocks.reshape(M, mb)
            wo_mb = write_offsets.reshape(M, mb)
            cos_all, sin_all = llama.rope_cos_sin(
                pos_mb, mcfg.head_dim, mcfg.rope_theta
            )                                         # [M, mb, d/2]
            xs = params["embed"][toks_mb]             # [M, mb, H]

            caches = [k_stack, v_stack]
            ys = jnp.zeros_like(xs)
            recv = jnp.zeros_like(xs[0])
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            for t in range(M + pp - 1):
                m = t - rank                          # this rank's microbatch
                mc = jnp.clip(m, 0, M - 1)
                valid = (m >= 0) & (m < M)
                x_own = jax.lax.dynamic_index_in_dim(
                    xs, jnp.minimum(t, M - 1), 0, keepdims=False
                )
                inp = jnp.where(rank == 0, x_own, recv)
                wb = jnp.where(valid, wb_mb[mc], jnp.zeros_like(wb_mb[0]))
                wo = jnp.where(valid, wo_mb[mc], jnp.zeros_like(wo_mb[0]))
                bt, sl = bt_mb[mc], sl_mb[mc]
                serve_layer = _make_serve_layer(
                    mcfg, tp, cos_all[mc][:, None, :], sin_all[mc][:, None, :]
                )

                def attend_one(q, k_new, v_new, kc, vc, wb=wb, wo=wo,
                               bt=bt, sl=sl):
                    kc, vc = att.write_decode_kv(kc, vc, k_new, v_new, wb, wo)
                    out = att.paged_decode_attention(q, kc, vc, bt, sl)
                    return out, kc, vc

                if cond_skip:
                    def do_stage(args):
                        x_in, kl, vl = args
                        return _stage_scan(
                            serve_layer, params["layers"], kl, vl, x_in,
                            attend_one,
                        )

                    def skip_stage(args):
                        return args  # activation + caches through untouched

                    out, k2, v2 = jax.lax.cond(
                        valid, do_stage, skip_stage,
                        (inp, caches[0], caches[1]),
                    )
                else:
                    # masked-write schedule: every tick computes; invalid
                    # ticks write scratch block 0 (wb/wo already masked)
                    out, k2, v2 = _stage_scan(
                        serve_layer, params["layers"], caches[0], caches[1],
                        inp, attend_one,
                    )
                caches = [k2, v2]
                # rank pp-1's tick-t output is microbatch t-(pp-1)
                m_out = t - (pp - 1)
                if 0 <= m_out < M:
                    ys = ys.at[m_out].set(
                        jnp.where(rank == pp - 1, out, ys[m_out])
                    )
                recv = jax.lax.ppermute(out, AXIS_PP, perm)
            # only rank pp-1 holds real outputs; broadcast them
            final = jnp.where(rank == pp - 1, ys, jnp.zeros_like(ys))
            hidden = jax.lax.psum(final, AXIS_PP).reshape(B, -1)
            hidden = _rms(hidden, params["final_norm"], mcfg.rms_norm_eps)
            return hidden, caches[0], caches[1]

        return run(params, k_stack, v_stack, tokens, positions, block_tables,
                   seq_lens, write_blocks, write_offsets)

    return fwd
