"""Device mesh + sharding helpers: the TPU-native parallelism substrate.

Where the reference passes TP/PP/EP sizes through to engine-internal NCCL
groups (components/src/dynamo/trtllm/engine.py:100-127, vllm/args.py:341),
this framework owns the model, so parallelism is expressed directly as a
``jax.sharding.Mesh`` with named axes and ``NamedSharding`` annotations; XLA
inserts the ICI collectives (psum for TP row-parallel, all-to-all for EP).

Axes:
    dp  — data parallel (replicated params, independent KV pools per rank)
    tp  — tensor parallel (heads/ffn sharded, psum over ICI)
    ep  — expert parallel (MoE experts sharded, all-to-all dispatch)
    sp  — sequence/context parallel (ring attention over long prefills)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_EP = "ep"
AXIS_SP = "sp"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with a version shim: older jaxlib ships it only as
    ``jax.experimental.shard_map`` with the ``check_vma`` knob spelled
    ``check_rep``. Every shard_map construction site in the package routes
    through here so the whole parallelism substrate (ring/sp, pp wavefront,
    EP psum, pallas sharding) serves on either jax generation."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as legacy_sm

    return legacy_sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    tp: int = 1,
    dp: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, sp, tp) mesh. tp innermost so TP collectives ride the
    fastest ICI links (nearest-neighbor within a slice row)."""
    devs = list(devices) if devices is not None else jax.devices()
    needed = tp * dp * sp
    if len(devs) < needed:
        raise ValueError(f"need {needed} devices (tp={tp} dp={dp} sp={sp}), have {len(devs)}")
    grid = np.array(devs[:needed]).reshape(dp, sp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_SP, AXIS_TP))


def single_device_mesh() -> Mesh:
    return make_mesh(tp=1, dp=1, sp=1, devices=jax.devices()[:1])


# -- canonical partition specs ---------------------------------------------
def param_specs_llama() -> dict:
    """PartitionSpecs for llama-family params (megatron-style TP).

    Column-parallel (shard output dim): q/k/v/gate/up projections, embedding.
    Row-parallel (shard input dim, psum after): o/down projections.
    """
    return {
        "embed": P(None, AXIS_TP),                 # [vocab, hidden] shard hidden
        "wq": P(None, AXIS_TP),                    # [hidden, heads*hd] shard heads
        "wk": P(None, AXIS_TP),
        "wv": P(None, AXIS_TP),
        "wo": P(AXIS_TP, None),                    # [heads*hd, hidden] row-parallel
        "w_gate": P(None, AXIS_TP),                # [hidden, inter]
        "w_up": P(None, AXIS_TP),
        "w_down": P(AXIS_TP, None),                # [inter, hidden]
        "norm": P(None),
        "lm_head": P(None, AXIS_TP),               # [hidden, vocab] shard vocab
    }


def kv_cache_spec() -> P:
    """Paged KV cache [num_blocks, block_size, kv_heads, head_dim]: shard the
    kv_heads axis across TP (each shard holds its own heads' cache)."""
    return P(None, None, AXIS_TP, None)


def shard(mesh: Mesh, spec: P):
    return NamedSharding(mesh, spec)


def tp_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS_TP]


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def mesh_info(mesh: Mesh) -> Tuple[int, int, int]:
    return mesh.shape[AXIS_DP], mesh.shape[AXIS_SP], mesh.shape[AXIS_TP]
