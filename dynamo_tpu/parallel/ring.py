"""Ring attention: blockwise context-parallel prefill over the ICI mesh.

The reference has no in-engine attention — long context is handled by
chunked prefill + P/D disaggregation + TRT-LLM context_parallel_size
passthrough (SURVEY.md §2.5 SP row; components/src/dynamo/trtllm/
engine.py:119). This framework owns its engine, so context parallelism is
implemented directly: the sequence is sharded over the ``sp`` mesh axis,
each device keeps its Q shard resident, and KV shards rotate around the
ring via ``ppermute`` while flash-style online-softmax accumulation folds
in one block per step. Peak memory per device is O(S/sp) and the KV
rotation rides nearest-neighbor ICI links concurrently with compute.

Causality across shards falls out of global position indices: the rotation
schedule pairs every Q shard with every KV shard exactly once, and blocks
strictly above the diagonal contribute nothing (fully masked).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AXIS_SP

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, m_prev, l_prev, acc_prev):
    """One online-softmax accumulation step.

    q [S,h,d] f32, k/v [T,kvh,d] f32, q_pos [S], k_pos [T].
    Carries: m,l [S,h,1], acc [S,h,d]."""
    S, h, d = q.shape
    T, kvh, _ = k.shape
    g = h // kvh
    scale = 1.0 / (d ** 0.5)
    qg = (q * scale).reshape(S, kvh, g, d)
    s = jnp.einsum("skgd,tkd->skgt", qg, k).reshape(S, h, T)
    mask = q_pos[:, None] >= k_pos[None, :]
    s = jnp.where(mask[:, None, :], s, NEG_INF)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    # fully-masked rows (block above the diagonal): keep carries unchanged
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pg = p.reshape(S, kvh, g, T)
    pv = jnp.einsum("skgt,tkd->skgd", pg, v).reshape(S, h, d)
    acc_new = alpha * acc_prev + pv
    return m_new, l_new, acc_new


def _ring_attention_shard(q, k, v, axis_name: str):
    """Per-shard body (inside shard_map): q,k,v are this device's sequence
    chunk [S_loc, heads, d] / [S_loc, kv_heads, d]."""
    sp = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    S_loc = q.shape[0]
    h = q.shape[1]
    d = q.shape[2]

    qf = q.astype(jnp.float32)
    q_pos = me * S_loc + jnp.arange(S_loc)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # after t rotations we hold the KV chunk originally on shard me - t
        src = jax.lax.rem(me - t + sp, sp)
        k_pos = src * S_loc + jnp.arange(S_loc)
        m, l, acc = _block_attend(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_pos, k_pos, m, l, acc,
        )
        # rotate for the next step (skipped on the final iteration by loop
        # bound; a wasted last permute would add one ICI hop of latency)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    m0 = jnp.full((S_loc, h, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S_loc, h, 1), jnp.float32)
    a0 = jnp.zeros((S_loc, h, d), jnp.float32)
    _, _, m, l, acc = jax.lax.fori_loop(0, sp, step, (k, v, m0, l0, a0))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_prefill_attention(
    mesh: Mesh,
    q: jax.Array,   # [S, h, d] global sequence (sharded or shardable on S)
    k: jax.Array,   # [S, kvh, d]
    v: jax.Array,
    sp_axis: str = AXIS_SP,
) -> jax.Array:
    """Causal self-attention over a long sequence, context-parallel over the
    ``sp`` mesh axis. S must divide evenly by the axis size (pad upstream).
    Degenerates to plain causal attention when the axis size is 1."""
    sp = mesh.shape[sp_axis]
    if q.shape[0] % sp:
        raise ValueError(f"sequence {q.shape[0]} not divisible by sp={sp}")
    fn = jax.shard_map(
        functools.partial(_ring_attention_shard, axis_name=sp_axis),
        mesh=mesh,
        in_specs=(P(sp_axis, None, None),) * 3,
        out_specs=P(sp_axis, None, None),
        check_vma=False,
    )
    return fn(q, k, v)
