"""Ring attention: blockwise context-parallel prefill over the ICI mesh.

The reference has no in-engine attention — long context is handled by
chunked prefill + P/D disaggregation + TRT-LLM context_parallel_size
passthrough (SURVEY.md §2.5 SP row; components/src/dynamo/trtllm/
engine.py:119). This framework owns its engine, so context parallelism is
implemented directly: the sequence is sharded over the ``sp`` mesh axis,
each device keeps its Q shard resident, and KV shards rotate around the
ring via ``ppermute`` while flash-style online-softmax accumulation folds
in one block per step. Peak memory per device is O(S/sp) and the KV
rotation rides nearest-neighbor ICI links concurrently with compute.

Causality across shards falls out of global position indices: the rotation
schedule pairs every Q shard with every KV shard exactly once, and blocks
strictly above the diagonal contribute nothing (fully masked).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AXIS_SP, shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, q_pos, k_pos, m_prev, l_prev, acc_prev):
    """One online-softmax accumulation step.

    q [S,h,d] f32, k/v [T,kvh,d] f32, q_pos [S], k_pos [T].
    Carries: m,l [S,h,1], acc [S,h,d]."""
    S, h, d = q.shape
    T, kvh, _ = k.shape
    g = h // kvh
    scale = 1.0 / (d ** 0.5)
    qg = (q * scale).reshape(S, kvh, g, d)
    s = jnp.einsum("skgd,tkd->skgt", qg, k).reshape(S, h, T)
    mask = q_pos[:, None] >= k_pos[None, :]
    s = jnp.where(mask[:, None, :], s, NEG_INF)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)
    # fully-masked rows (block above the diagonal): keep carries unchanged
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pg = p.reshape(S, kvh, g, T)
    pv = jnp.einsum("skgt,tkd->skgd", pg, v).reshape(S, h, d)
    acc_new = alpha * acc_prev + pv
    return m_new, l_new, acc_new


def _ring_attention_shard(q, k, v, axis_name: str):
    """Per-shard body (inside shard_map): q,k,v are this device's sequence
    chunk [S_loc, heads, d] / [S_loc, kv_heads, d]."""
    sp = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    S_loc = q.shape[0]
    h = q.shape[1]
    d = q.shape[2]

    qf = q.astype(jnp.float32)
    q_pos = me * S_loc + jnp.arange(S_loc)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # after t rotations we hold the KV chunk originally on shard me - t
        src = jax.lax.rem(me - t + sp, sp)
        k_pos = src * S_loc + jnp.arange(S_loc)
        m, l, acc = _block_attend(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_pos, k_pos, m, l, acc,
        )

        # rotate for the next step; guarded so the final iteration skips the
        # permute (its result would never be read — one wasted ICI hop per
        # layer otherwise)
        def rotate(kv):
            k_cur, v_cur = kv
            return (
                jax.lax.ppermute(k_cur, axis_name, perm),
                jax.lax.ppermute(v_cur, axis_name, perm),
            )

        k_nxt, v_nxt = jax.lax.cond(
            t + 1 < sp, rotate, lambda kv: kv, (k_cur, v_cur)
        )
        return k_nxt, v_nxt, m, l, acc

    m0 = jnp.full((S_loc, h, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S_loc, h, 1), jnp.float32)
    a0 = jnp.zeros((S_loc, h, d), jnp.float32)
    _, _, m, l, acc = jax.lax.fori_loop(0, sp, step, (k, v, m0, l0, a0))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_prefill_attention(
    mesh: Mesh,
    q: jax.Array,   # [S, h, d] global sequence (sharded or shardable on S)
    k: jax.Array,   # [S, kvh, d]
    v: jax.Array,
    sp_axis: str = AXIS_SP,
) -> jax.Array:
    """Causal self-attention over a long sequence, context-parallel over the
    ``sp`` mesh axis. S must divide evenly by the axis size (pad upstream).
    Degenerates to plain causal attention when the axis size is 1."""
    sp = mesh.shape[sp_axis]
    if q.shape[0] % sp:
        raise ValueError(f"sequence {q.shape[0]} not divisible by sp={sp}")
    fn = shard_map(
        functools.partial(_ring_attention_shard, axis_name=sp_axis),
        mesh=mesh,
        in_specs=(P(sp_axis, None, None),) * 3,
        out_specs=P(sp_axis, None, None),
        check_vma=False,
    )
    return fn(q, k, v)


def _ring_extend_shard(
    q, k, v, q_pos, k_ctx, v_ctx, ctx_len, chunk_start, axis_name: str
):
    """Per-shard body for prefix-extend ring attention (inside shard_map).

    q/k/v: this device's chunk shard [S_loc, heads/kv, d]; q_pos [S_loc]
    absolute positions. k_ctx/v_ctx: the cached-prefix pages (replicated,
    [T_ctx, kvh, d]) of which the first ``ctx_len`` rows are valid.
    chunk_start: absolute position of the chunk's first token."""
    sp = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    S_loc, h, d = q.shape

    qf = q.astype(jnp.float32)
    m = jnp.full((S_loc, h, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((S_loc, h, 1), jnp.float32)
    acc = jnp.zeros((S_loc, h, d), jnp.float32)

    # 1) attend the cached prefix locally (pages are replicated across sp;
    #    gather rows past ctx_len are garbage — push their k_pos beyond any
    #    query so the causal mask kills them)
    T_ctx = k_ctx.shape[0]
    if T_ctx > 0:
        ctx_pos = jnp.arange(T_ctx)
        ctx_pos = jnp.where(ctx_pos < ctx_len, ctx_pos, jnp.int32(2**30))
        m, l, acc = _block_attend(
            qf, k_ctx.astype(jnp.float32), v_ctx.astype(jnp.float32),
            q_pos, ctx_pos, m, l, acc,
        )

    # 2) ring over the chunk's own KV shards
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        src = jax.lax.rem(me - t + sp, sp)
        k_pos = chunk_start + src * S_loc + jnp.arange(S_loc)
        m, l, acc = _block_attend(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            q_pos, k_pos, m, l, acc,
        )

        def rotate(kv):
            k_cur, v_cur = kv
            return (
                jax.lax.ppermute(k_cur, axis_name, perm),
                jax.lax.ppermute(v_cur, axis_name, perm),
            )

        k_nxt, v_nxt = jax.lax.cond(
            t + 1 < sp, rotate, lambda kv: kv, (k_cur, v_cur)
        )
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(0, sp, step, (k, v, m, l, acc))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_extend_attention(
    mesh: Mesh,
    q: jax.Array,        # [S, h, d] chunk queries (shardable on S)
    k_new: jax.Array,    # [S, kvh, d] chunk keys
    v_new: jax.Array,
    k_ctx: jax.Array,    # [T_ctx, kvh, d] gathered prefix pages (replicated)
    v_ctx: jax.Array,
    q_positions: jax.Array,  # [S] absolute positions
    ctx_len: jax.Array,      # scalar: valid prefix length (== chunk start)
    chunk_start: jax.Array,  # scalar: absolute position of chunk token 0
    sp_axis: str = AXIS_SP,
) -> jax.Array:
    """Prefix-extend attention for chunked prefill, context-parallel over the
    ``sp`` axis: the engine's long-context prefill path (VERDICT r1 item 2).
    Each device holds S/sp of the chunk's queries+KV; chunk KV rotates around
    the ring while the cached-prefix pages are attended locally. The merge is
    a single online-softmax accumulation chain, so the result is exactly
    ``extend_attention`` over (prefix ++ chunk)."""
    sp = mesh.shape[sp_axis]
    if q.shape[0] % sp:
        raise ValueError(f"chunk {q.shape[0]} not divisible by sp={sp}")
    fn = shard_map(
        functools.partial(_ring_extend_shard, axis_name=sp_axis),
        mesh=mesh,
        in_specs=(
            P(sp_axis, None, None),   # q
            P(sp_axis, None, None),   # k_new
            P(sp_axis, None, None),   # v_new
            P(sp_axis),               # q_pos
            P(None, None, None),      # k_ctx
            P(None, None, None),      # v_ctx
            P(),                      # ctx_len
            P(),                      # chunk_start
        ),
        out_specs=P(sp_axis, None, None),
        check_vma=False,
    )
    return fn(q, k_new, v_new, q_positions, k_ctx, v_ctx, ctx_len, chunk_start)
