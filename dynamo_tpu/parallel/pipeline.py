"""Pipeline parallelism: GPipe microbatching over a ``pp`` mesh axis.

Where the reference forwards a pipeline-parallel size into its engines' NCCL
groups (components/src/dynamo/trtllm/engine.py:100-127 pipeline_parallel_size,
vllm/args.py), this framework owns the model, so PP is a JAX transform:

- layer params are **stacked** along a leading layer axis and sharded over
  ``pp`` — each pipeline rank physically holds only its own stage's layers;
- the forward is a ``shard_map`` schedule: M microbatches flow through
  ``M + pp - 1`` ticks, activations hop rank->rank via ``lax.ppermute``
  (nearest-neighbor ICI traffic only), every tick each rank applies its
  local stage (a ``lax.scan`` over its layers);
- TP composes inside the stage (megatron-style: column-parallel qkv/gate/up,
  row-parallel wo/down followed by ``psum`` over tp); DP composes outside
  (batch sharded over dp, loss ``pmean``'d);
- the whole schedule is built from ``lax.scan`` so it is **differentiable**:
  one ``jax.value_and_grad`` through the pipeline gives correct gradients
  (ppermute transposes to the reverse permute — the backward pipeline).

Collectives ride the mesh exactly as the scaling-book recipe prescribes:
activation hops and grad psum over ICI neighbors, nothing bounces off DCN.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from .mesh import AXIS_DP, AXIS_TP, shard_map

AXIS_PP = "pp"

Params = Dict[str, Any]


def make_pp_mesh(
    pp: int,
    tp: int = 1,
    dp: int = 1,
    devices=None,
) -> Mesh:
    """(dp, pp, tp) mesh: tp innermost (fastest ICI for per-layer psum),
    pp middle (nearest-neighbor activation hops), dp outermost."""
    devs = list(devices) if devices is not None else jax.devices()
    needed = pp * tp * dp
    if len(devs) < needed:
        raise ValueError(f"need {needed} devices (pp={pp} tp={tp} dp={dp}), have {len(devs)}")
    grid = np.array(devs[:needed]).reshape(dp, pp, tp)
    return Mesh(grid, (AXIS_DP, AXIS_PP, AXIS_TP))


# ---------------------------------------------------------------------------
# param stacking + specs
# ---------------------------------------------------------------------------

_LAYER_TP_SPECS = {
    # [L, ...] stacked layer weights; dim 0 shards over pp
    "attn_norm": P(AXIS_PP, None),
    "mlp_norm": P(AXIS_PP, None),
    "wq": P(AXIS_PP, None, AXIS_TP),
    "wk": P(AXIS_PP, None, AXIS_TP),
    "wv": P(AXIS_PP, None, AXIS_TP),
    # qkv biases shard like the matching projection's output dim
    "bq": P(AXIS_PP, AXIS_TP),
    "bk": P(AXIS_PP, AXIS_TP),
    "bv": P(AXIS_PP, AXIS_TP),
    # per-head q/k RMSNorm weights [L, head_dim]: replicated over tp
    "q_norm": P(AXIS_PP, None),
    "k_norm": P(AXIS_PP, None),
    "wo": P(AXIS_PP, AXIS_TP, None),
    "w_gate": P(AXIS_PP, None, AXIS_TP),
    "w_up": P(AXIS_PP, None, AXIS_TP),
    "w_down": P(AXIS_PP, AXIS_TP, None),
}

_TOP_SPECS = {
    # embeddings/norm replicated: vocab matmuls are a tiny share of a
    # pipelined model's weights, and replication keeps first/last stage
    # logic uniform across ranks
    "embed": P(None, None),
    "final_norm": P(None),
    "lm_head": P(None, None),
}


def stack_params(params: Params) -> Params:
    """List-of-layer-dicts -> dict of [L, ...] stacked arrays (+ top-level
    params unchanged). The stacked form is what shards over pp."""
    layers = params["layers"]
    stacked = {
        name: jnp.stack([lp[name] for lp in layers]) for name in layers[0]
    }
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = stacked
    return out


def unstack_params(stacked: Params) -> Params:
    L = next(iter(stacked["layers"].values())).shape[0]
    layers = [
        {name: w[i] for name, w in stacked["layers"].items()} for i in range(L)
    ]
    out = {k: v for k, v in stacked.items() if k != "layers"}
    out["layers"] = layers
    return out


def stacked_param_specs(stacked: Params) -> Params:
    specs = {
        k: _TOP_SPECS.get(k, P(None)) for k in stacked if k != "layers"
    }
    specs["layers"] = {
        name: _LAYER_TP_SPECS.get(name, P(AXIS_PP, None))
        for name in stacked["layers"]
    }
    return specs


def place_stacked(mesh: Mesh, stacked: Params) -> Params:
    # PartitionSpec subclasses tuple, so tree-mapping over a spec tree would
    # recurse into the specs themselves — walk the (flat) dicts by key
    specs = stacked_param_specs(stacked)

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    out = {
        k: put(v, specs[k]) for k, v in stacked.items() if k != "layers"
    }
    out["layers"] = {
        name: put(w, specs["layers"][name])
        for name, w in stacked["layers"].items()
    }
    return out


# ---------------------------------------------------------------------------
# in-stage layer math (manual TP inside shard_map)
# ---------------------------------------------------------------------------


def _rms(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _layer_apply(lp: Params, cfg: llama.LlamaConfig, tp: int, x, cos, sin):
    """One transformer layer on local TP shards. x: [mb, S, H] replicated over
    tp; wq/wk/wv/w_gate/w_up are column-sharded, wo/w_down row-sharded with a
    psum to complete the contraction (megatron TP, parallel/mesh.py specs)."""
    d = cfg.head_dim
    hl = cfg.num_heads // tp       # local q heads
    kvl = cfg.num_kv_heads // tp   # local kv heads
    g = hl // kvl
    mb, S, _ = x.shape

    h = _rms(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(mb, S, hl, d)
    k = (h @ lp["wk"]).reshape(mb, S, kvl, d)
    v = (h @ lp["wv"]).reshape(mb, S, kvl, d)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)

    # GQA causal attention, f32 softmax
    qg = q.reshape(mb, S, kvl, g, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bsKgd,btKd->bKgst", qg, kf) / jnp.sqrt(float(d))
    causal = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(causal[None, None, None], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bKgst,btKd->bsKgd", w, v.astype(jnp.float32))
    o = o.reshape(mb, S, hl * d).astype(x.dtype)
    o = o @ lp["wo"]                      # [mb, S, H] partial sum over shards
    x = x + jax.lax.psum(o, AXIS_TP)

    h = _rms(x, lp["mlp_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32)).astype(x.dtype)
    up = h @ lp["w_up"]
    down = (gate * up) @ lp["w_down"]     # partial
    return x + jax.lax.psum(down, AXIS_TP)


# ---------------------------------------------------------------------------
# the pipeline schedule
# ---------------------------------------------------------------------------


def pipeline_loss_fn(
    mesh: Mesh,
    cfg: llama.LlamaConfig,
    num_microbatches: int,
) -> Callable[[Params, jax.Array], jax.Array]:
    """Next-token cross-entropy through the pp/tp/dp pipeline.

    Returns ``loss_fn(stacked_params, tokens)`` with tokens ``[B, S]``
    (B sharded over dp). Differentiable end-to-end."""
    pp = mesh.shape[AXIS_PP]
    tp = mesh.shape[AXIS_TP]
    M = num_microbatches
    if cfg.num_layers % pp:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by pp {pp}")
    if cfg.num_heads % tp or cfg.num_kv_heads % tp:
        raise ValueError("heads not divisible by tp")
    if cfg.qk_norm or cfg.qkv_bias:
        raise NotImplementedError("pipeline stage math covers the plain llama layer")
    if not cfg.tie_embeddings:
        raise NotImplementedError("pipeline head assumes tied embeddings")

    def local_fn(layers_local: Params, embed, final_norm, tokens_local):
        # layers_local: [L/pp, ...] this rank's stage; tokens_local: [b, S]
        rank = jax.lax.axis_index(AXIS_PP)
        b, S = tokens_local.shape
        if b % M:
            raise ValueError(f"per-dp batch {b} not divisible by microbatches {M}")
        mb = b // M
        H = cfg.hidden_size

        x_all = embed[tokens_local]                      # [b, S, H]
        x_mb = x_all.reshape(M, mb, S, H)
        positions = jnp.arange(S)
        cos, sin = llama.rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
        cos, sin = cos[:, None, :], sin[:, None, :]      # bcast over heads

        def stage(x):
            def body(h, lp):
                return _layer_apply(lp, cfg, tp, h, cos, sin), None

            out, _ = jax.lax.scan(body, x, layers_local)
            return out

        # GPipe: M + pp - 1 ticks; rank 0 injects microbatch t, rank pp-1
        # emits microbatch t-(pp-1); activations hop ranks via ppermute
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        ticks = M + pp - 1

        def tick(carry, t):
            recv, out = carry
            inject = x_mb[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(rank == 0, inject, recv)
            h_out = stage(h_in)
            idx = t - (pp - 1)
            write = (rank == pp - 1) & (idx >= 0) & (idx < M)
            slot = jnp.clip(idx, 0, M - 1)
            out = out.at[slot].set(jnp.where(write, h_out, out[slot]))
            recv = jax.lax.ppermute(h_out, AXIS_PP, perm)
            return (recv, out), None

        recv0 = jnp.zeros((mb, S, H), x_all.dtype)
        out0 = jnp.zeros((M, mb, S, H), x_all.dtype)
        (_, out), _ = jax.lax.scan(
            tick, (recv0, out0), jnp.arange(ticks)
        )
        # results live on the last pp rank; psum replicates them (cheap at
        # dryrun scale; a production LM head would stay stage-local)
        out = jax.lax.psum(
            jnp.where(rank == pp - 1, out, jnp.zeros_like(out)), AXIS_PP
        )
        hidden = _rms(out.reshape(b, S, H), final_norm, cfg.rms_norm_eps)
        logits = (hidden @ embed.T).astype(jnp.float32)  # [b, S, V] (tied)

        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = tokens_local[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        loss = jnp.mean(nll)
        return jax.lax.pmean(loss, AXIS_DP)

    specs = None

    def loss_fn(stacked: Params, tokens: jax.Array) -> jax.Array:
        nonlocal specs
        if specs is None:
            specs = stacked_param_specs(stacked)
        fn = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(
                specs["layers"],
                specs["embed"],
                specs["final_norm"],
                P(AXIS_DP, None),
            ),
            out_specs=P(),
            check_vma=False,
        )
        return fn(
            stacked["layers"], stacked["embed"], stacked["final_norm"], tokens
        )

    return loss_fn


def make_train_step(
    mesh: Mesh,
    cfg: llama.LlamaConfig,
    num_microbatches: int = 2,
    learning_rate: float = 1e-3,
):
    """(step_fn, init_opt_state): jitted SGD-with-momentum training step over
    the pp/tp/dp mesh. step(stacked, opt_state, tokens) -> (stacked,
    opt_state, loss)."""
    loss_fn = pipeline_loss_fn(mesh, cfg, num_microbatches)

    def init_opt_state(stacked: Params) -> Params:
        return jax.tree.map(jnp.zeros_like, stacked)

    @jax.jit
    def step(stacked: Params, opt_state: Params, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(stacked, tokens)
        opt_state = jax.tree.map(
            lambda m, g: 0.9 * m + g.astype(m.dtype), opt_state, grads
        )
        stacked = jax.tree.map(
            lambda p, m: p - learning_rate * m.astype(p.dtype), stacked, opt_state
        )
        return stacked, opt_state, loss

    return step, init_opt_state
