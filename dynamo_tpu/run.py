"""python -m dynamo_tpu.run — single-command wiring of inputs to engines.

Analog of the reference's dynamo-run CLI (launch/dynamo-run/src/main.rs:30-33,
opt.rs:6-17: `dynamo-run in=<input> out=<engine>`): everything in one
process with in-proc planes — the fastest way to poke a model or script a
batch, no services to stand up.

    python -m dynamo_tpu.run in=text:"hello world" out=tiny
    python -m dynamo_tpu.run in=stdin out=mocker
    python -m dynamo_tpu.run in=batch:prompts.txt out=qwen3-0.6b --max-tokens 32
    python -m dynamo_tpu.run in=http:8000 out=tiny        # OpenAI frontend

Engines (`out=`): echo | mocker | any model preset | a local HF checkpoint
path. Inputs (`in=`): text:<prompt> | stdin | batch:<file> (one prompt per
line, results as JSONL on stdout) | http:<port>.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import Any, AsyncIterator

from .llm import ModelDeploymentCard, register_llm
from .llm.protocols.common import BackendOutput
from .runtime import DistributedRuntime, RuntimeConfig, init_logging
from .runtime.engine import Context


class EchoEngine:
    """Reference engines.rs:67 make_echo_engine: tokens in, tokens out."""

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        req = request if isinstance(request, dict) else request.to_obj()
        for tid in req.get("token_ids", []):
            yield BackendOutput(token_ids=[tid])
        yield BackendOutput(finish_reason="stop", token_ids=[])


def _build_engine(out: str, args):
    if out == "echo":
        return EchoEngine(), "byte", 4096
    if out == "mocker":
        from .mocker.engine import MockEngineArgs, MockerEngine

        return MockerEngine(MockEngineArgs(speedup_ratio=args.speedup)), "byte", 4096
    from .engine.engine import TpuEngine, TpuEngineConfig
    from .engine import __main__ as engine_main

    if out in engine_main.PRESETS:
        mcfg = engine_main.PRESETS[out]()
        params, tokenizer = None, "byte"
    else:  # a local HF checkpoint directory or hub reference (llm/hub.py)
        from .engine.warm import load_params_warm
        from .engine.weights import config_from_hf
        from .llm.hub import resolve_model_path

        out = resolve_model_path(out)
        mcfg = config_from_hf(out)
        params = load_params_warm(out, mcfg)
        tokenizer = out
    cfg = TpuEngineConfig(
        model=mcfg, max_context=args.max_context,
        num_blocks=max(512, (args.max_context // 16) * 16),
        prefill_buckets=tuple(
            b for b in (64, 128, 256, 512, 1024, 2048) if b < args.max_context
        ) + (args.max_context,),
    )
    return TpuEngine(cfg, params=params), tokenizer, args.max_context


async def _serve(engine, tokenizer: str, ctx_len: int, model: str):
    rt = await DistributedRuntime(
        RuntimeConfig(store="mem", event_plane="inproc")
    ).start()
    card = ModelDeploymentCard(
        name=model, tokenizer=tokenizer, kv_block_size=16, context_length=ctx_len,
    )
    await register_llm(rt, engine, card)
    return rt, card


async def _client_pipeline(rt, card):
    from .llm.discovery import ModelManager, ModelWatcher

    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    for _ in range(200):
        if manager.get(card.name) is not None:
            break
        await asyncio.sleep(0.02)
    pipeline = manager.get(card.name)
    if pipeline is None:
        raise RuntimeError(f"model {card.name!r} never appeared in discovery")
    return watcher, manager, pipeline


async def _gen_text(pipeline, model: str, prompt: str, args) -> AsyncIterator[str]:
    from .llm.protocols.openai import CompletionRequest

    req = CompletionRequest(
        model=model, prompt=prompt, max_tokens=args.max_tokens, stream=True,
        temperature=args.temperature,
    )
    preq = pipeline.preprocessor.preprocess_completion(req, prompt)
    ctx = Context(preq.request_id)
    try:
        async for out in pipeline.generate_tokens(preq, ctx):
            if out.text:
                yield out.text
            if out.finish_reason is not None:
                return
    finally:
        ctx.stop_generating()


async def run(args) -> None:
    init_logging()
    kind, _, val = args.input.partition(":")
    engine, tokenizer, ctx_len = _build_engine(args.out, args)
    model = args.model or (args.out if not args.out.startswith("/") else "local")
    rt, card = await _serve(engine, tokenizer, ctx_len, model)

    if kind == "http":
        from .llm.discovery import ModelManager, ModelWatcher
        from .llm.http.service import HttpService

        manager = ModelManager()
        await ModelWatcher(rt, manager).start()
        svc = HttpService(manager, port=int(val or 8000))
        await svc.start()
        print(f"OpenAI frontend on :{svc.port} serving {model!r} (ctrl-c to stop)",
              file=sys.stderr)
        try:
            await asyncio.Event().wait()
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass
        await svc.stop()
        return

    watcher, manager, pipeline = await _client_pipeline(rt, card)
    try:
        if kind == "text":
            async for delta in _gen_text(pipeline, model, val, args):
                print(delta, end="", flush=True)
            print()
        elif kind == "stdin":
            print(f"interactive with {model!r} — empty line quits", file=sys.stderr)
            loop = asyncio.get_running_loop()
            while True:
                line = await loop.run_in_executor(None, sys.stdin.readline)
                line = (line or "").strip()
                if not line:
                    break
                async for delta in _gen_text(pipeline, model, line, args):
                    print(delta, end="", flush=True)
                print()
        elif kind == "batch":
            with open(val) as f:
                prompts = [l.rstrip("\n") for l in f if l.strip()]
            for n, prompt in enumerate(prompts):
                chunks = []
                async for delta in _gen_text(pipeline, model, prompt, args):
                    chunks.append(delta)
                print(json.dumps({"index": n, "prompt": prompt,
                                  "text": "".join(chunks)}))
        else:
            raise SystemExit(f"unknown input {args.input!r} "
                             "(text:<prompt> | stdin | batch:<file> | http:<port>)")
    finally:
        await watcher.stop()
        for p in manager.pipelines():
            await p.stop()
        if hasattr(engine, "stop"):
            engine.stop()
        await rt.shutdown()


def main() -> None:
    p = argparse.ArgumentParser(
        "dynamo_tpu.run",
        usage='python -m dynamo_tpu.run in=<input> out=<engine> [options]',
    )
    p.add_argument("io", nargs=2, metavar="in=|out=",
                   help="in=text:<p>|stdin|batch:<f>|http:<port>  "
                        "out=echo|mocker|<preset>|<hf-dir>")
    p.add_argument("--model", default=None, help="served model name")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--max-context", type=int, default=2048)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--speedup", type=float, default=1.0, help="mocker clock")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    args = p.parse_args()

    spec = {}
    for part in args.io:
        k, _, v = part.partition("=")
        spec[k] = v
    if "in" not in spec or "out" not in spec:
        p.error("need both in=<input> and out=<engine>")
    args.input, args.out = spec["in"], spec["out"]
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
