"""Scenario builder: N mocker workers behind the real control plane.

One process, one virtual clock: each ``SimPool`` stands up mocker engines
(``mocker/engine.py``) publishing real KV events + worker metrics onto an
in-proc event plane, routed by a real ``KvRouter``, observed by a real
``EventPlaneMetricsSource`` feeding a real ``PoolPlanner`` whose decisions
resize the fleet (extending ``profiler/loadgen.planner_sim`` from a one-off
validation into the subsystem's closed loop). Per-worker ``CircuitBreaker``s
steer traffic around flapping workers exactly like the frontend does
(``llm/discovery.py _tripped``), and flaps themselves come from the PR 1
fault registry (points ``sim.worker.<id>``, seeded schedules) so chaos is
reproducible.

Everything that paces — arrivals, engine steps, planner windows, breaker
reset timers, worker boot — rides the injected ``Clock``; under
``sim.clock.run`` a minutes-long trace replays in seconds and two same-seed
runs produce identical request records. The only wall-clock quantity kept is
the router *decision latency* (``time.perf_counter_ns`` around
``schedule_tokens``): that is a real control-plane CPU cost this harness
exists to measure (ROADMAP item 3), and it is reported in the separate
non-deterministic ``wall`` section of the scenario report.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..engine.checkpoint import (
    CheckpointCorrupt,
    load_checkpoint,
    save_checkpoint,
)
from ..kv_router import (
    KvEventPublisher,
    KvRouter,
    KvRouterConfig,
    WorkerMetricsPublisher,
    WorkerWithDpRank,
)
from ..kvbm.directory import GlobalKvDirectory
from ..ops.costs import fetch_vs_recompute
from ..llm.protocols.common import (
    FINISH_ERROR,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..mocker.engine import MockEngineArgs, MockerEngine
from ..planner.core import LoadSnapshot, PlannerConfig, PoolPlanner
from ..planner.metrics_source import (
    EventPlaneMetricsSource,
    FrontendStatsPublisher,
)
from ..profiler.loadgen import prefix_prompt
from ..runtime import metrics as M
from ..runtime.bandwidth import WireBandwidthEstimator
from ..runtime.discovery.store import MemKVStore
from ..runtime.engine import Context
from ..runtime.event_plane.base import InProcEventPlane
from ..runtime.faults import FAULTS, FaultInjected, parse_faults
from ..runtime.logging import get_logger
from ..runtime.resilience import CLOSED, OPEN, CircuitBreaker
from ..runtime.slo import SlaSpec, SloAccountant
from ..tokens import compute_sequence_hashes
from .clock import Clock
from .traces import SimRequest

log = get_logger("sim.fleet")


def worker_fault_point(worker_id: int) -> str:
    """Fault-registry point name for one simulated worker's serving path."""
    return f"sim.worker.{worker_id}"


# -- planned-reclaim evacuation model (drain_worker) -------------------------
# wire classes per worker: even ids sit a native hop from the rest of the
# pool, odd ids only reach it over a congested inline path — the skew the
# cost-priced destination choice must react to (same shape the disagg
# scenario uses for its prefill pool)
_EVAC_WIRE_PRIORS = {"native": 2.0e9, "inline": 1.0e8}
# large-model scale (a 16-token page of KV across all layers runs tens of
# MB): at this size the normalized wire term lands in the same block units
# as the scheduler's overlap/load logit instead of vanishing under it, so
# a congested wire genuinely loses the destination pick
_EVAC_KV_BYTES_PER_BLOCK = 32 * 1024 * 1024

# the mocker has no KV tensors, so checkpoint files carry a 16-byte
# deterministic stand-in per sealed page — but they round-trip through the
# REAL engine/checkpoint.py writer and G3 block-file codec, so chaos faults
# and corruption detection exercise the production path
_SIM_BLOCK_FORMAT = {"kind": "float", "dtype": "uint8", "shape": [16]}

# -- fleet-wide KV reuse (kvbm/directory.py, global-kv-reuse scenario) -------
# the peer-tier fetch rides its own "tier" wire class: a G2 host-memory read
# streamed over the block-window protocol runs near line rate, which is what
# makes fetch beat recompute for multi-block prefixes (ops/costs.py)
_GLOBAL_KV_WIRE_PRIORS = {"tier": 2.0e9}


def evac_wire_for(wid: int) -> str:
    return "native" if wid % 2 == 0 else "inline"


def _sim_block_payload(h: int) -> np.ndarray:
    return np.frombuffer(
        (int(h) & ((1 << 64) - 1)).to_bytes(8, "little") * 2, dtype=np.uint8
    ).copy()


@dataclasses.dataclass
class PoolConfig:
    """One worker pool (a namespace with its own router and planner)."""

    name: str = "pool0"
    namespace: str = "sim"
    component: str = "backend"
    initial_workers: int = 4
    min_workers: int = 1
    max_workers: int = 64
    # mocker sizing
    block_size: int = 16
    num_blocks: int = 4096
    max_num_seqs: int = 64
    max_num_batched_tokens: int = 8192
    startup_time_s: float = 5.0        # simulated boot time of a new worker
    # mocker timing model: deliberately slow per-worker speeds so hundreds
    # of workers are *needed* at realistic request rates while the step
    # count (= python cost) stays low
    prefill_base_s: float = 0.05
    prefill_per_token_s: float = 5e-4
    decode_base_s: float = 0.05
    decode_per_kv_block_s: float = 1e-5
    # router pruning overrides (None/0 -> KvRouterConfig defaults): top-K
    # candidate pruning + postings shard count (docs/operations.md)
    router_topk: Optional[int] = None
    router_shards: Optional[int] = None
    # metric-staleness horizon override: a slowed worker's step (and thus
    # its publish cadence) can outlast the default 10s window, making a
    # backed-up worker score as idle exactly while it is drowning — the
    # degradation scenario stretches this past its slowest step period
    router_stale_s: Optional[float] = None
    # planner (autoscale=False -> fixed fleet of initial_workers)
    autoscale: bool = False
    adjustment_interval_s: float = 10.0
    capacity_req_s: float = 1.0        # per-worker sustainable req/s profile
    expected_ttft_s: float = 0.0       # >0 -> measured/expected correction
    queue_bump_divisor: float = 4.0
    scale_down_headroom: float = 0.8
    max_scale_down_frac: float = 0.5   # bounded descent (planner/core.py)
    predictor: str = "holt"
    # router
    overlap_weight: float = 1.0
    router_temperature: float = 0.0
    # per-worker breakers (llm/discovery.py analog)
    breaker_threshold: int = 3
    breaker_window_s: float = 60.0
    breaker_reset_s: float = 30.0


@dataclasses.dataclass
class FleetConfig:
    seed: int = 0
    prefix_share: float = 0.5          # shared fraction of each group prompt
    max_attempts: int = 3              # retry-then-migrate bound per request
    faults: str = ""                   # DTPU_FAULTS-style spec armed for the run
    # fleet-wide KV reuse (kvbm/directory.py): OFF by default so every
    # existing scenario's report stays byte-identical; the global-kv-reuse
    # scenario (and its counterfactual twin) flips it
    global_kv: bool = False
    global_kv_ttl_s: float = 120.0     # directory-entry ts aging (virtual)
    global_kv_dedupe: int = 2          # holders per hash before publish skips
    global_kv_margin: float = 1.0      # fetch <= margin * recompute bound
    # wire bytes per fetched block (small-model scale: a 16-token page at
    # this size keeps the tier wire term visible without drowning prefill)
    global_kv_bytes_per_block: int = 2 * 1024 * 1024
    pools: List[PoolConfig] = dataclasses.field(
        default_factory=lambda: [PoolConfig()]
    )


@dataclasses.dataclass
class RequestRecord:
    idx: int
    group: int
    region: str
    pool: str
    sla_class: str
    t_arrive: float
    isl: int
    osl: int
    ttft_target_s: float
    itl_target_s: float
    worker: int = -1
    ttft_s: float = -1.0
    itl_sum_s: float = 0.0
    itl_count: int = 0
    cached_tokens: int = 0
    input_tokens: int = 0
    produced: int = 0
    attempts: int = 0
    ok: bool = False

    @property
    def itl_mean_s(self) -> float:
        return self.itl_sum_s / self.itl_count if self.itl_count else 0.0


@dataclasses.dataclass
class SimWorker:
    wid: int
    engine: MockerEngine
    breaker: CircuitBreaker
    spawned_at: float
    requests: int = 0
    last_state: str = CLOSED


class _PoolConnector:
    """Planner connector resizing a SimPool (the closed loop's actuator)."""

    def __init__(self, pool: "SimPool"):
        self.pool = pool

    async def get_replicas(self, component: str) -> int:
        return len(self.pool.workers)

    async def set_replicas(self, component: str, n: int) -> None:
        self.pool.resize(n)


class SimPool:
    def __init__(self, fleet: "SimFleet", cfg: PoolConfig, seed: int):
        self.fleet = fleet
        self.cfg = cfg
        self.clock: Clock = fleet.clock
        self.plane = fleet.plane
        self.workers: Dict[int, SimWorker] = {}
        self._next_wid = 1
        # wid -> WorkerWithDpRank, cached: _candidates builds a ~fleet-sized
        # list per routing decision and dataclass construction dominates it
        self._cands: Dict[int, WorkerWithDpRank] = {}
        kv_overrides = {}
        if cfg.router_topk is not None:
            kv_overrides["topk_candidates"] = cfg.router_topk
        if cfg.router_shards is not None:
            kv_overrides["index_shards"] = cfg.router_shards
        if cfg.router_stale_s is not None:
            kv_overrides["metrics_stale_after_s"] = cfg.router_stale_s
        self.router = KvRouter(
            self.plane, cfg.namespace, cfg.component,
            block_size=cfg.block_size,
            config=KvRouterConfig(
                overlap_score_weight=cfg.overlap_weight,
                router_temperature=cfg.router_temperature,
                **kv_overrides,
            ),
            seed=seed,
            # staleness/TTL/sync-jitter timing rides the virtual clock
            clock=self.clock,
        )
        self.stats_pub = FrontendStatsPublisher(
            self.plane, cfg.namespace, clock=self.clock.time
        )
        # the REAL SLO accountant (runtime/slo.py) on the virtual clock:
        # scenario SLA invariants read per-class attainment from here —
        # the same code path the frontend serves on /debug/slo. Objective
        # pinned (not env) so reports stay a pure function of the seed.
        self.slo = SloAccountant(clock=self.clock.time, objective=0.99)
        self.metrics_source: Optional[EventPlaneMetricsSource] = None
        self.planner: Optional[PoolPlanner] = None
        # workers that ever recorded a failure: the only ones whose breaker
        # can be OPEN, so per-request breaker checks skip the healthy fleet
        self._suspects: set = set()
        # workers in their drain window (drain_worker): excluded from
        # routing like OPEN breakers — the sim analog of the discovery
        # record flipping to "draining" (llm/discovery.py _draining)
        self._draining: set = set()
        self.drain_log: List[Dict] = []
        self.evacuated_blocks_total = 0
        self.evac_dest_wires: List[str] = []
        # fleet-wide KV reuse (FleetConfig.global_kv): per-worker directory
        # clients (holder key "pool/wid" — wids collide across pools) plus
        # the deterministic counters detail.global_cache reports
        self._dirs: Dict[int, GlobalKvDirectory] = {}
        self.global_fetch_events = 0
        self.global_fetched_blocks = 0
        self.global_recomputed_blocks = 0
        self.global_stale_skips = 0
        self.global_resumed_fetches = 0
        # -- deterministic outputs -------------------------------------------
        self.records: List[RequestRecord] = []
        self.itls: List[float] = []
        self.replica_timeline: List[List[float]] = []   # [t, replicas]
        self.correction_timeline: List[float] = []
        self.breaker_events: List[List] = []            # [t, wid, state]
        self.fanout: List[int] = []                     # candidates/decision
        # -- wall-clock outputs (real control-plane CPU cost) ----------------
        self.decision_wall_ns: List[int] = []

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> "SimPool":
        await self.router.start()
        for _ in range(self.cfg.initial_workers):
            self._spawn(startup_s=0.0)  # the initial fleet is already booted
        if self.cfg.autoscale:
            self.metrics_source = await EventPlaneMetricsSource(
                self.plane, self.cfg.namespace, [self.cfg.component],
                clock=self.clock.time,
            ).start()
            self.planner = PoolPlanner(
                self.cfg.name, self.cfg.component, _PoolConnector(self),
                PlannerConfig(
                    adjustment_interval_s=self.cfg.adjustment_interval_s,
                    predictor=self.cfg.predictor,
                    min_replicas=self.cfg.min_workers,
                    max_replicas=self.cfg.max_workers,
                    queue_bump_divisor=self.cfg.queue_bump_divisor,
                    scale_down_headroom=self.cfg.scale_down_headroom,
                    max_scale_down_frac=self.cfg.max_scale_down_frac,
                ),
                capacity_fn=lambda snap: self.cfg.capacity_req_s,
            )
            self.fleet.spawn_task(self._planner_loop())
        return self

    async def stop(self) -> None:
        if self.metrics_source is not None:
            self.metrics_source.stop()
        for w in self.workers.values():
            w.engine.stop()
        await self.router.stop()

    def _spawn(self, startup_s: Optional[float] = None) -> int:
        wid = self._next_wid
        self._next_wid += 1
        cfg = self.cfg
        args = MockEngineArgs(
            num_blocks=cfg.num_blocks, block_size=cfg.block_size,
            max_num_seqs=cfg.max_num_seqs,
            max_num_batched_tokens=cfg.max_num_batched_tokens,
            emit_sim_ts=True, speedup_ratio=1.0,
            startup_time_s=(
                cfg.startup_time_s if startup_s is None else startup_s
            ),
            prefill_base_s=cfg.prefill_base_s,
            prefill_per_token_s=cfg.prefill_per_token_s,
            decode_base_s=cfg.decode_base_s,
            decode_per_kv_block_s=cfg.decode_per_kv_block_s,
        )
        engine = MockerEngine(
            args,
            kv_publisher=KvEventPublisher(
                self.plane, cfg.namespace, cfg.component,
                worker_id=wid, block_size=cfg.block_size,
            ),
            metrics_publisher=WorkerMetricsPublisher(
                self.plane, cfg.namespace, cfg.component,
                worker_id=wid, clock=self.clock.time,
            ),
            clock=self.fleet.clock,
        )
        # per-worker breaker on the virtual clock (discovery.py analog);
        # detached metrics scope — worker ids churn under autoscaling
        breaker = CircuitBreaker(
            name=f"sim.{cfg.name}.worker.{wid}",
            failure_threshold=cfg.breaker_threshold,
            failure_rate=0.5,
            window_s=cfg.breaker_window_s,
            reset_timeout_s=cfg.breaker_reset_s,
            metrics=self.fleet.breaker_metrics,
            clock=self.clock.time,
        )
        self.workers[wid] = SimWorker(
            wid, engine, breaker, spawned_at=self.clock.time()
        )
        self._cands[wid] = WorkerWithDpRank(wid, 0)
        # candidate-free routing: the router's universe tracks spawns (and
        # _retire's remove_worker_id untracks), so submit passes only an
        # exclusion set — O(K) per decision instead of a fleet-sized list
        self.router.register_worker(self._cands[wid])
        if self.fleet.kv_store is not None:
            # no store lease in the sim: entry liveness rides the injected-
            # clock ts (deterministic), and a killed worker's stale ads are
            # exactly what the dead-holder fallback path must survive
            self._dirs[wid] = GlobalKvDirectory(
                self.fleet.kv_store, f"{self.cfg.name}/{wid}",
                ttl_s=self.fleet.cfg.global_kv_ttl_s,
                dedupe_replicas=self.fleet.cfg.global_kv_dedupe,
                clock=self.clock.time,
            )
        return wid

    def resize(self, n: int) -> None:
        n = max(self.cfg.min_workers, min(self.cfg.max_workers, n))
        while len(self.workers) < n:
            self._spawn()
        while len(self.workers) > n:
            # retire newest-first (LIFO, mirrors FleetConnector.pop):
            # the oldest workers hold the warmest radix caches
            self._retire(max(self.workers))

    def _retire(self, wid: int) -> None:
        w = self.workers.pop(wid)
        self._cands.pop(wid, None)
        self._draining.discard(wid)
        self.router.remove_worker_id(wid)
        d = self._dirs.pop(wid, None)
        if d is not None and d.published_count:
            # orderly scale-down withdraws its advertisements (the prod
            # analog is the lease revoke in GlobalKvDirectory.close)
            self.fleet.spawn_task(d.withdraw_all())
        self.fleet.spawn_task(self._drain_stop(w))

    async def _drain_stop(self, w: SimWorker) -> None:
        try:
            while True:
                s = w.engine.snapshot()
                if not s["waiting"] and not s["running"]:
                    break
                await self.clock.sleep(0.25)
        finally:
            # retired workers are no longer in self.workers, so pool.stop()
            # can't reach them — stop the engine even if the drain is
            # cancelled at fleet shutdown
            w.engine.stop()
            # the draining engine kept publishing metrics, which re-register
            # the retired worker in the router's universe as a zero-load
            # ghost; de-register once it can publish no more
            self.router.remove_worker_id(w.wid)

    # -- planned reclaims (docs/operations.md §13) ----------------------------
    async def drain_worker(
        self,
        wid: int,
        deadline_s: float = 30.0,
        *,
        ckpt_dir: Optional[str] = None,
        margin_s: float = 2.0,
        stream_window: int = 8,
        bandwidth: Optional[WireBandwidthEstimator] = None,
    ) -> Dict:
        """Planned death of one worker — the sim analog of
        engine/drain.py's DrainCoordinator.begin: flag it draining (new
        routing stops immediately, like the discovery-record flip), let
        short in-flight decodes run out, bulk-evacuate its sealed KV to
        cost-priced destinations in block-window units (the PR 10 streamed
        protocol; a dropped ``transfer.stream_window`` resumes per block),
        checkpoint through the REAL engine/checkpoint.py writer inside the
        deadline margin, then hard-kill at the deadline — still-running
        decodes get FINISH_ERROR and the submit loop migrates them."""
        w = self.workers.get(wid)
        if w is None:
            return {"wid": wid, "state": "gone"}
        await FAULTS.ainject("drain.notice")
        t0 = self.clock.time()
        t_kill = t0 + deadline_s
        self._draining.add(wid)
        if self.metrics_source is not None:
            # announced reclaims ride LoadSnapshot.announced_reclaims so
            # the planner pre-warms replacements (planner/core.py)
            self.metrics_source.note_reclaim(wid, t_kill)
        bw = bandwidth or WireBandwidthEstimator(priors=dict(_EVAC_WIRE_PRIORS))
        block_time_s = self.cfg.prefill_per_token_s * self.cfg.block_size
        summary: Dict = {
            "wid": wid, "t_notice": round(t0, 3), "deadline_s": deadline_s,
            "evacuated": 0, "resumed_windows": 0, "ckpt": "skipped",
            "quiesced": False, "killed_in_flight": 0,
        }
        # ---- mass KV evacuation: sealed (evictable) pages, oldest first ----
        hashes = list(w.engine.kv.cached)
        for lo in range(0, len(hashes), stream_window):
            if self.clock.time() >= t_kill - margin_s:
                break  # notice budget spent: keep the checkpoint margin
            batch = hashes[lo : lo + stream_window]
            move_bytes = len(batch) * _EVAC_KV_BYTES_PER_BLOCK
            # destinations priced by bandwidth EWMA in block-time units —
            # the same extra_costs currency the prefill router uses — NOT
            # round-robin; overlap on the window's hashes dedups re-sends
            costs = {
                cand: bw.transfer_seconds(evac_wire_for(w2), move_bytes)
                / block_time_s
                for w2, cand in self._cands.items()
                if w2 != wid
            }
            decision = self.router.score_tokens(
                [], hashes=batch, extra_costs=costs,
                excluded=self._excluded(()),
            )
            dest = self.workers.get(decision.worker.worker_id)
            if dest is None or dest.wid == wid:
                break
            wire = evac_wire_for(dest.wid)
            wire_s = bw.transfer_seconds(wire, move_bytes)
            await self.clock.sleep(wire_s)
            bw.observe(wire, move_bytes, wire_s)
            try:
                await FAULTS.ainject("transfer.stream_window")
            except (ConnectionError, FaultInjected):
                # dropped mid-window: the block-window protocol resumes
                # from the last acked block, re-sending the tail per block
                # — costs one more window of wire time, loses nothing
                summary["resumed_windows"] += 1
                await self.clock.sleep(wire_s)
            fresh = []
            for h in batch:
                if h in dest.engine.kv.active or h in dest.engine.kv.cached:
                    continue
                if dest.engine.kv.free_blocks <= 0:
                    break
                dest.engine.kv.cached[h] = None
                fresh.append(h)
            if fresh and dest.engine.kv_publisher is not None:
                # publish directly (not via events_stored): an idle
                # destination engine only drains events when it next serves
                await dest.engine.kv_publisher.stored(fresh)
            summary["evacuated"] += len(batch)
            self.evac_dest_wires.append(wire)
        self.evacuated_blocks_total += summary["evacuated"]
        # ---- short in-flight decodes run to completion ----
        while self.clock.time() < t_kill - margin_s:
            s = w.engine.snapshot()
            if not s["waiting"] and not s["running"]:
                summary["quiesced"] = True
                break
            await self.clock.sleep(0.25)
        # ---- checkpoint inside the margin (REAL writer: faults fire) ----
        if ckpt_dir is not None:
            try:
                save_checkpoint(
                    ckpt_dir,
                    [(h, _sim_block_payload(h)) for h in w.engine.kv.cached],
                    block_format=dict(_SIM_BLOCK_FORMAT),
                    queue=[
                        {"request_id": st.req.request_id,
                         "produced": st.produced}
                        for st in (w.engine._waiting + w.engine._running)
                    ],
                    weights_ref=f"sim-{self.cfg.name}",
                )
                summary["ckpt"] = "ok"
            except (FaultInjected, ConnectionError, OSError) as e:
                # died mid-commit: no manifest lands, so restore classifies
                # the directory as a partial checkpoint and cold-boots
                summary["ckpt"] = f"failed:{type(e).__name__}"
        summary["margin_s"] = round(t_kill - self.clock.time(), 3)
        # ---- checkpointed-out workers leave the directory cleanly ----
        d = self._dirs.get(wid)
        if d is not None and d.published_count:
            summary["directory_withdrawn"] = await d.withdraw_all()
        # ---- the reclaim fires at the deadline ----
        dt = t_kill - self.clock.time()
        if dt > 0:
            await self.clock.sleep(dt)
        s = w.engine.snapshot()
        summary["killed_in_flight"] = s["waiting"] + s["running"]
        self.kill_worker(wid)
        self.drain_log.append(summary)
        return summary

    def kill_worker(self, wid: int) -> None:
        """The reclaim itself: hard-stop NOW. Unlike :meth:`_retire` there
        is no graceful wait — still-running streams get FINISH_ERROR from
        the dying loop and the submit retry loop migrates them (zero lost
        requests is the scenario's invariant, not a kindness of the
        kill)."""
        w = self.workers.pop(wid, None)
        self._draining.discard(wid)
        self._suspects.discard(wid)
        self._cands.pop(wid, None)
        self.router.remove_worker_id(wid)
        # NOT withdrawn: a hard-killed worker leaves stale directory ads
        # behind (the TTL ages them; until then lookups must survive them)
        self._dirs.pop(wid, None)
        if w is not None:
            w.engine.stop()

    async def restore_worker(
        self, ckpt_dir: str, *, startup_s: Optional[float] = None
    ) -> Dict:
        """Boot a replacement from a checkpoint (the sim analog of
        engine/__main__.py's restore_engine wiring). A committed manifest
        restores WARM: the replacement pre-seeds the checkpointed sealed
        pages into its prefix cache and announces them to the router, so
        the fleet's working set survives the reclaim. Anything short of
        that — absent or partial manifest, torn blocks — detects as
        corrupt and boots COLD (full prefix rebuild), never serving
        garbage pages."""
        blocks: List[int] = []
        reason = ""
        try:
            state = load_checkpoint(ckpt_dir)
        except CheckpointCorrupt as e:
            reason = str(e)
        else:
            for h in state.blocks:
                try:
                    state.load_block(h)  # validate against the block format
                except CheckpointCorrupt as e:
                    reason = str(e)  # keep the intact warm prefix
                    break
                blocks.append(h)
        wid = self._spawn(startup_s=startup_s)
        eng = self.workers[wid].engine
        seeded: List[int] = []
        for h in blocks:
            if eng.kv.free_blocks <= 0:
                break
            eng.kv.cached[h] = None
            seeded.append(h)
        if seeded and eng.kv_publisher is not None:
            await eng.kv_publisher.stored(seeded)
        return {
            "wid": wid,
            "mode": "warm" if seeded else "cold",
            "blocks": len(seeded),
            "reason": reason,
        }

    # -- the closed loop -----------------------------------------------------
    async def _planner_loop(self) -> None:
        assert self.planner is not None and self.metrics_source is not None
        while True:
            await self.clock.sleep(self.cfg.adjustment_interval_s)
            snap: LoadSnapshot = self.metrics_source.snapshot()
            self.planner.observe(snap.request_rate)
            if self.cfg.expected_ttft_s > 0 and snap.measured_ttft > 0:
                self.planner.update_correction(
                    snap.measured_ttft, self.cfg.expected_ttft_s
                )
            try:
                await self.planner.plan_and_apply(snap)
            except Exception:
                log.exception("sim planner tick failed (pool %s)", self.cfg.name)
            self.replica_timeline.append(
                [round(self.clock.time(), 3), len(self.workers)]
            )
            self.correction_timeline.append(round(self.planner.correction, 4))

    # -- request path --------------------------------------------------------
    def _candidates(self, excluded=()) -> List[WorkerWithDpRank]:
        """Live workers minus open breakers minus this request's already-
        failed workers — unless that empties the pool (then a tripped
        worker beats no worker; llm/discovery.py _tripped + Migration's
        excluded-instance list). Kept for scenarios that need the explicit
        list (the disagg planner's stub client); the hot submit path routes
        by exclusion set instead (:meth:`_excluded`)."""
        avoid = [
            wid for wid, w in self.workers.items()
            if wid in excluded or wid in self._draining
            or w.breaker.state == OPEN
        ]
        eligible = [wid for wid in self.workers if wid not in avoid]
        if not eligible:
            eligible = list(self.workers)
        return [self._cands[wid] for wid in eligible]

    def _excluded(self, tried) -> set:
        """The exclusion set for one routing decision: this request's
        already-failed workers plus open breakers. Only ``_suspects``
        (workers with at least one recorded failure) can possibly be OPEN,
        so the scan is O(failures seen), not O(fleet) — the submit path
        must stay sublinear in fleet size at 10k workers. Returns empty
        when exclusion would cover the whole pool (a tripped worker beats
        no worker; the router applies the same fallback internally)."""
        avoid = set()
        for wid in tried:
            c = self._cands.get(wid)
            if c is not None:
                avoid.add(c)
        for wid in list(self._suspects):
            w = self.workers.get(wid)
            if w is None:
                self._suspects.discard(wid)
                continue
            if w.breaker.state == OPEN:
                avoid.add(self._cands[wid])
        for wid in list(self._draining):
            c = self._cands.get(wid)
            if c is None:
                self._draining.discard(wid)
                continue
            avoid.add(c)
        if len(avoid) >= len(self.workers):
            return set()
        return avoid

    def _note_breaker(self, w: SimWorker) -> None:
        state = w.breaker.state
        if state != w.last_state:
            self.breaker_events.append(
                [round(self.clock.time(), 3), w.wid, state]
            )
            w.last_state = state

    # -- fleet-wide KV reuse (FleetConfig.global_kv) --------------------------
    async def _global_fetch(
        self, wid: int, w: SimWorker, tokens: List[int]
    ) -> None:
        """Onboard-from-peer-tier before prefill: on a local radix miss,
        look up the missing leading blocks in the fleet directory, price
        fetching the longest single-holder run against recomputing it
        (ops/costs.fetch_vs_recompute on the tier-wire EWMA), and when
        fetch wins, seed the blocks into this worker's prefix cache after
        the simulated wire time — the mocker then skips that prefill. A
        holder that died after advertising (stale entry inside the TTL)
        falls back to recompute; no path here can fail the request."""
        d = self._dirs.get(wid)
        bw = self.fleet.global_bw
        if d is None or bw is None:
            return
        fcfg = self.fleet.cfg
        hashes = compute_sequence_hashes(tokens, self.cfg.block_size)
        have = w.engine.kv.cached_prefix_len(hashes)
        miss = hashes[have:]
        if not miss:
            return
        try:
            run = await d.lookup_run(miss, exclude_holder=d.holder)
        except (ConnectionError, FaultInjected):
            # directory.lookup chaos: an unreachable directory degrades to
            # plain per-worker radix, never to a failed request
            self.global_recomputed_blocks += len(miss)
            d.record_outcome("recomputed")
            return
        if not run:
            # nobody advertises the miss: a plain local miss, not a
            # fetch-vs-recompute decision
            self.global_recomputed_blocks += len(miss)
            return
        verdict = fetch_vs_recompute(
            len(run),
            block_size=self.cfg.block_size,
            kv_bytes_per_block=fcfg.global_kv_bytes_per_block,
            bandwidth_bytes_s=bw.bandwidth("tier"),
            prefill_base_s=self.cfg.prefill_base_s,
            prefill_per_token_s=self.cfg.prefill_per_token_s,
            tier=run[0].tier,
            margin=fcfg.global_kv_margin,
        )
        if not verdict["fetch_wins"]:
            self.global_recomputed_blocks += len(run)
            d.record_outcome("recomputed")
            return
        holder = run[0].holder
        pool_name, _, holder_wid = holder.rpartition("/")
        src_pool = self.fleet.pools.get(pool_name)
        src = (
            src_pool.workers.get(int(holder_wid))
            if src_pool is not None else None
        )
        n_run, n_miss = len(run), len(miss)
        move_bytes = n_run * fcfg.global_kv_bytes_per_block
        wire_s = bw.transfer_seconds("tier", move_bytes)
        run_hashes = [e.hash for e in run]
        dropped = False
        try:
            await FAULTS.ainject("fetch.peer_tier")
        except (ConnectionError, FaultInjected):
            # dropped mid-stream: the block-window protocol resumes from
            # the last acked block (engine/transfer.py _pull_tier) — one
            # extra pass of wire time, no block lost, request unharmed
            dropped = True
        lease = d.begin_fetch(holder, run_hashes)
        if src is None:
            # the advertised holder is dead (hard kill leaves its entries
            # until the TTL): abort the fetch lease and recompute
            d.abort_fetch(lease)
            self.global_stale_skips += 1
            self.global_recomputed_blocks += n_run
            return
        n_fresh = 0
        try:
            if dropped:
                self.global_resumed_fetches += 1
                await self.clock.sleep(wire_s)
            await self.clock.sleep(wire_s)
            bw.observe("tier", move_bytes, wire_s)
            fresh: List[int] = []
            for h in run_hashes:
                if h in w.engine.kv.active or h in w.engine.kv.cached:
                    continue
                if w.engine.kv.free_blocks <= 0:
                    break
                w.engine.kv.cached[h] = None
                fresh.append(h)
            n_fresh = len(fresh)
            if fresh and w.engine.kv_publisher is not None:
                # publish directly (not via events_stored): an idle
                # destination engine only drains events when it next serves
                await w.engine.kv_publisher.stored(fresh)
        except BaseException:
            # cancellation (fleet teardown) mid-fetch: the lease must not
            # strand — abort counts the miss as recomputed
            d.abort_fetch(lease)
            raise
        d.commit_fetch(lease, n_fresh)
        self.global_fetch_events += 1
        self.global_fetched_blocks += n_fresh
        self.global_recomputed_blocks += n_miss - n_fresh

    async def _publish_global(self, wid: int, tokens: List[int]) -> None:
        """Advertise the sealed blocks a completed request left in this
        worker's prefix cache ("g2" — the mocker has no real tiers).
        Dedupe inside GlobalKvDirectory bounds hot prefixes to
        ``global_kv_dedupe`` holders fleet-wide."""
        d = self._dirs.get(wid)
        w = self.workers.get(wid)
        if d is None or w is None:
            return
        hashes = compute_sequence_hashes(tokens, self.cfg.block_size)
        held = [
            h for h in hashes
            if h in w.engine.kv.active or h in w.engine.kv.cached
        ]
        try:
            await d.publish(held, "g2")
        except (ConnectionError, FaultInjected):
            pass  # directory.publish chaos: one advertisement lost, that's all

    async def submit(
        self, idx: int, sreq: SimRequest,
        tokens: Optional[List[int]] = None,
    ) -> RequestRecord:
        """``tokens`` overrides the trace-derived prompt (the disagg
        scenario submits only the un-transferred tail to the decode pool)."""
        item = sreq.item
        if tokens is None:
            tokens = prefix_prompt(item, idx, self.fleet.cfg.prefix_share)
        t_arrive = self.clock.time()
        rec = RequestRecord(
            idx=idx, group=item.group, region=sreq.region, pool=self.cfg.name,
            sla_class=sreq.sla_class,
            t_arrive=round(t_arrive, 6), isl=item.isl, osl=item.osl,
            ttft_target_s=sreq.ttft_target_s, itl_target_s=sreq.itl_target_s,
        )
        tried: set = set()
        while rec.attempts < self.fleet.cfg.max_attempts:
            rec.attempts += 1
            if not self.workers:
                break
            excluded = self._excluded(tried)
            rid = f"sim-{self.cfg.name}-{idx}.a{rec.attempts}"
            t0 = time.perf_counter_ns()
            # candidate-free routing over the router's registered universe:
            # the decision (prune + exact rescore) is the measured
            # control-plane cost, with no O(fleet) list build around it
            decision = self.router.schedule_tokens(
                tokens, excluded=excluded, request_id=rid
            )
            self.decision_wall_ns.append(time.perf_counter_ns() - t0)
            self.fanout.append(len(self.workers) - len(excluded))
            wid = decision.worker.worker_id
            w = self.workers.get(wid)
            ok = False
            try:
                # seeded flap injection on this worker's serving path
                await FAULTS.ainject(worker_fault_point(wid))
                if w is None:
                    # retired between decision and dispatch — or a ghost a
                    # draining engine's metrics resurrected: de-register so
                    # the zero-load ghost can't keep winning least-loaded
                    self.router.remove_worker_id(wid)
                    raise ConnectionError(f"sim worker {wid} gone")
                if self.fleet.kv_store is not None:
                    await self._global_fetch(wid, w, tokens)
                ok = await self._consume(w.engine, rid, tokens, item, rec, t_arrive)
            except (ConnectionError, FaultInjected):
                ok = False
            finally:
                self.router.complete(rid)
            if not ok:
                # exclude on ANY failure, raised or not (FINISH_ERROR frame,
                # stream ending without a finish) — otherwise radix affinity
                # re-picks the same dead worker every attempt
                tried.add(wid)
                self._suspects.add(wid)
            if w is not None:
                w.breaker.record(ok)
                self._note_breaker(w)
            if ok:
                rec.ok = True
                rec.worker = wid
                w.requests += 1
                if self.fleet.kv_store is not None:
                    await self._publish_global(wid, tokens)
                # feed the production accountant with the record's own
                # promise — the per-class ledger the invariants assert on
                met = self.slo.record(
                    "sim",
                    SlaSpec(rec.sla_class, rec.ttft_target_s,
                            rec.itl_target_s),
                    ttft_s=rec.ttft_s,
                    itl_s=(rec.itl_mean_s if rec.itl_count else None),
                    output_tokens=rec.produced,
                    e2e_s=self.clock.time() - t_arrive,
                )
                # the real stack's frontend stats fan-out: planner
                # correction factors read these measured latencies, and the
                # accountant verdict rides along like the HTTP frontend's
                self.stats_pub.on_request(
                    prompt_tokens=rec.input_tokens or len(tokens),
                    completion_tokens=rec.produced,
                    ttft_s=rec.ttft_s,
                    itl_s=rec.itl_mean_s,
                    sla_class=rec.sla_class,
                    ttft_target_s=rec.ttft_target_s,
                    itl_target_s=rec.itl_target_s,
                    sla_met=met,
                )
                break
        self.records.append(rec)
        return rec

    async def _consume(
        self,
        engine: MockerEngine,
        rid: str,
        tokens: List[int],
        item,
        rec: RequestRecord,
        t_arrive: float,
    ) -> bool:
        req = PreprocessedRequest(
            request_id=rid, model="sim", token_ids=tokens,
            stop=StopConditions(
                max_tokens=item.osl, min_tokens=item.osl, ignore_eos=True
            ),
            sampling=SamplingOptions(temperature=0.0),
        )
        t_prev: Optional[float] = None
        produced = 0
        async for out in engine.generate(req, Context(rid)):
            if out.finish_reason == FINISH_ERROR:
                return False
            if not out.token_ids:
                continue
            now = self.clock.time()
            if t_prev is None:
                # serving TTFT on the one shared timeline: includes queueing,
                # worker boot and routing retries, not just engine compute
                rec.ttft_s = now - t_arrive
                rec.cached_tokens = out.annotations.get("cached_tokens", 0)
                rec.input_tokens = out.annotations.get("input_tokens", 0)
            else:
                gap = now - t_prev
                self.itls.append(gap)
                rec.itl_sum_s += gap
                rec.itl_count += 1
            t_prev = now
            produced += len(out.token_ids)
            if out.finish_reason is not None:
                rec.produced = produced
                return True
        return False  # stream ended without a finish frame: worker died


class SimFleet:
    """All pools + the shared event plane + run-wide fault arming."""

    def __init__(self, cfg: FleetConfig, clock: Clock):
        self.cfg = cfg
        self.clock = clock
        self.plane = InProcEventPlane()
        self.breaker_metrics = M.MetricsScope()  # detached from /metrics
        # fleet-wide KV reuse: one shared directory plane (MemKVStore, the
        # in-proc stand-in for the discovery/netstore store) + one bandwidth
        # EWMA for the "tier" wire class — None unless cfg.global_kv, so the
        # hot submit path of every existing scenario is untouched
        self.kv_store = MemKVStore() if cfg.global_kv else None
        self.global_bw = (
            WireBandwidthEstimator(priors=dict(_GLOBAL_KV_WIRE_PRIORS))
            if cfg.global_kv else None
        )
        self.pools: Dict[str, SimPool] = {
            p.name: SimPool(self, p, seed=cfg.seed + i)
            for i, p in enumerate(cfg.pools)
        }
        self._tasks: List[asyncio.Task] = []
        self._armed_points: List[str] = []

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> "SimFleet":
        if self.cfg.faults:
            self.arm_faults(self.cfg.faults)
        for pool in self.pools.values():
            await pool.start()
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        for pool in self.pools.values():
            await pool.stop()
        await self.plane.close()
        for point in self._armed_points:
            FAULTS.disarm(point)
        # disarm(point) keeps the point's fired-event history (the live log is
        # a cross-rule determinism record) — but a finished sim run must leave
        # the process-global registry exactly as it found it, or a later
        # chaos test's exact-schedule assertion sees our fires prepended
        armed = set(self._armed_points)
        if armed:
            FAULTS.fired = [f for f in FAULTS.fired if f[0] not in armed]
        self._armed_points = []

    def spawn_task(self, coro) -> asyncio.Task:
        t = asyncio.create_task(coro)
        self._tasks.append(t)
        return t

    # -- chaos ---------------------------------------------------------------
    def arm_faults(self, spec: str) -> None:
        """Arm a DTPU_FAULTS-grammar spec for this run (points are disarmed
        and their call counters reset on stop, so back-to-back same-seed
        runs see identical schedules)."""
        for rule in parse_faults(spec):
            FAULTS.arm_rule(rule)
            if rule.point not in self._armed_points:
                self._armed_points.append(rule.point)

    def disarm_fault(self, point: str) -> None:
        FAULTS.disarm(point)
        if point in self._armed_points:
            self._armed_points.remove(point)

    # -- driving -------------------------------------------------------------
    @property
    def default_pool(self) -> SimPool:
        return next(iter(self.pools.values()))

    async def run_trace(
        self,
        trace: List[SimRequest],
        pool_for: Optional[Callable[[SimRequest], str]] = None,
    ) -> None:
        """Replay ``trace`` at virtual arrival pacing, fanning each request
        into its pool (``pool_for`` defaults to the first pool)."""
        tasks: List[asyncio.Task] = []
        t_prev = 0.0
        for idx, sreq in enumerate(trace):
            dt = sreq.t - t_prev
            t_prev = sreq.t
            if dt > 0:
                await self.clock.sleep(dt)
            pool = (
                self.pools[pool_for(sreq)] if pool_for is not None
                else self.default_pool
            )
            tasks.append(asyncio.create_task(pool.submit(idx, sreq)))
        if tasks:
            await asyncio.gather(*tasks)
