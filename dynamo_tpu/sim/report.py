"""Scenario reports: deterministic sim metrics + wall control-plane cost.

A scenario report has two sections with a hard contract:

- ``sim`` — every value is a pure function of (scenario, seed, knobs):
  simulated TTFT/ITL percentiles, SLA attainment, cache hit ratio, replica
  and breaker timelines, routing fan-out, and the machine-checked
  invariants. Two same-seed runs must serialize this section byte-for-byte
  identically (``canonical_json``; tests/test_sim.py pins it). Two
  scenarios are documented exceptions whose *invariants* assert bounded
  wall-measured behavior (``router-scale-sublinear`` latency ratios,
  ``http-frontend`` real-socket counts) — they are excluded from the
  byte-identity pins; their remaining sim values stay seed-deterministic.

- ``wall`` — real CPU cost of the control plane measured during the run:
  router decision latency percentiles, elapsed wall seconds, virtual
  seconds driven. Host-dependent by nature; excluded from the determinism
  comparison exactly like run timestamps.

``bench_record`` folds a scenario-suite run into the one-line BENCH JSON
schema (metric/value/unit/vs_baseline/detail) bench.py prints, so the sim
gate gives every PR a perf verdict even with the device bench down.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from ..profiler.loadgen import pct
from .fleet import SimFleet, SimPool


@dataclasses.dataclass
class Invariant:
    """One machine-checked closed-loop property of a scenario."""

    name: str
    ok: bool
    detail: str

    def to_obj(self) -> dict:
        return {"name": self.name, "ok": self.ok, "detail": self.detail}


def _dist_ms(xs: List[float]) -> Dict[str, float]:
    xs = sorted(xs)
    n = len(xs)
    return {
        "n": n,
        "mean_ms": round(sum(xs) / n * 1e3, 3) if n else 0.0,
        "p50_ms": round(pct(xs, 0.50) * 1e3, 3),
        "p95_ms": round(pct(xs, 0.95) * 1e3, 3),
        "p99_ms": round(pct(xs, 0.99) * 1e3, 3),
    }


def direction_flips(
    timeline: List[int], min_step: int = 2, min_frac: float = 0.1
) -> int:
    """Significant resize-direction changes in a replica timeline — the
    oscillation measure the no-flapping invariant bounds (a clean diurnal
    cycle is one up-run + one down-run = 1 flip per period). Moves smaller
    than ``min_step`` workers or ``min_frac`` of the pool are operating
    noise (a 1-worker wobble on a 12-worker fleet) and neither count as a
    flip nor establish a direction."""
    flips = 0
    prev_dir = 0
    for a, b in zip(timeline, timeline[1:]):
        delta = b - a
        if abs(delta) <= max(min_step, min_frac * max(a, 1)):
            continue
        d = 1 if delta > 0 else -1
        if prev_dir != 0 and d != prev_dir:
            flips += 1
        prev_dir = d
    return flips


def pool_report(pool: SimPool) -> dict:
    """Deterministic per-pool metrics from the run's request records.

    Memoized: scenarios call this for their invariants and scenario_report
    calls it again to serialize — the cache makes both reads the same
    O(records) aggregation (and the same dict) instead of two. The key
    covers every input stream (records, replica/breaker/itl/fanout
    timelines) so a mid-run sampler never sees a stale report."""
    key = (
        len(pool.records), len(pool.replica_timeline),
        len(pool.breaker_events), len(pool.itls), len(pool.fanout),
        len(pool.correction_timeline),
    )
    cached_rep = getattr(pool, "_report_cache", None)
    if cached_rep is not None and cached_rep[0] == key:
        return cached_rep[1]
    recs = pool.records
    done = [r for r in recs if r.ok]
    ttfts = [r.ttft_s for r in done if r.ttft_s >= 0]
    replicas = [n for _, n in pool.replica_timeline]
    per_worker: Dict[str, int] = {}
    per_group_ttft: Dict[int, List[float]] = {}
    for r in done:
        per_worker[str(r.worker)] = per_worker.get(str(r.worker), 0) + 1
        per_group_ttft.setdefault(r.group, []).append(r.ttft_s)
    cached = sum(r.cached_tokens for r in done)
    inputs = sum(r.input_tokens for r in done)
    itl_target = _itl_target(pool)
    rep = {
        "workers_final": len(pool.workers),
        "requests": len(recs),
        "completed": len(done),
        "failed": len(recs) - len(done),
        "retries": sum(r.attempts - 1 for r in recs),
        "ttft": _dist_ms(ttfts),
        "itl": _dist_ms(pool.itls),
        "ttft_attainment": round(
            sum(1 for r in done if r.ttft_s <= r.ttft_target_s)
            / max(len(done), 1), 4,
        ),
        "itl_attainment": round(
            sum(1 for g in pool.itls if g <= itl_target) /
            max(len(pool.itls), 1), 4,
        ),
        "cache_hit_ratio": round(cached / max(inputs, 1), 4),
        "per_worker_requests": dict(sorted(per_worker.items())),
        "group_ttft_p95_ms": {
            str(g): round(pct(sorted(v), 0.95) * 1e3, 3)
            for g, v in sorted(per_group_ttft.items())
        },
        "fanout_mean": round(
            sum(pool.fanout) / max(len(pool.fanout), 1), 2
        ),
        "replicas": {
            "timeline": pool.replica_timeline,
            "min": min(replicas) if replicas else len(pool.workers),
            "max": max(replicas) if replicas else len(pool.workers),
            "final": replicas[-1] if replicas else len(pool.workers),
            "direction_flips": direction_flips(replicas),
        },
        "correction_final": (
            pool.correction_timeline[-1] if pool.correction_timeline else 1.0
        ),
        "breaker_events": pool.breaker_events,
        # the production SloAccountant's ledger (fed per completed request
        # on the virtual clock): per-class windows, burn rates, goodput —
        # scenario SLA invariants read these instead of re-deriving math
        "slo": _slo_section(pool),
    }
    if pool.fleet.cfg.global_kv:
        # fleet-wide KV reuse counters — keyed ONLY when the run had the
        # directory on, so every pre-existing scenario's canonical_json
        # pin stays byte-identical
        rep["global_cache"] = _global_cache_section(pool)
    pool._report_cache = (key, rep)
    return rep


def _global_cache_section(pool: SimPool) -> dict:
    fetched = pool.global_fetched_blocks
    recomputed = pool.global_recomputed_blocks
    dedupe = sum(d.dedupe_skipped for d in pool._dirs.values())
    published = sum(d.published_count for d in pool._dirs.values())
    return {
        "fetch_events": pool.global_fetch_events,
        "fetched_blocks": fetched,
        "recomputed_blocks": recomputed,
        "fetched_fraction": round(fetched / max(fetched + recomputed, 1), 4),
        "stale_holder_skips": pool.global_stale_skips,
        "resumed_fetches": pool.global_resumed_fetches,
        "dedupe_skipped_blocks": dedupe,
        "dedupe_ratio": round(dedupe / max(dedupe + published, 1), 4),
        "directory_entries": published,
    }


def _slo_section(pool: SimPool) -> dict:
    snap = pool.slo.snapshot()
    return {
        "objective": snap["objective"],
        "classes": snap["models"].get("sim", {}),
    }


def _itl_target(pool: SimPool) -> float:
    done = [r for r in pool.records if r.ok]
    return done[0].itl_target_s if done else 0.05


def pool_wall_report(pool: SimPool) -> dict:
    ns = sorted(pool.decision_wall_ns)
    return {
        "router_decisions": len(ns),
        "router_decision_us": {
            "p50": round(pct(ns, 0.50) / 1e3, 1),
            "p99": round(pct(ns, 0.99) / 1e3, 1),
        },
    }


def scenario_report(
    name: str,
    seed: int,
    fleet: SimFleet,
    invariants: List[Invariant],
    sim_duration_s: float,
    wall_elapsed_s: float,
    extra_sim: Optional[dict] = None,
    sim_advanced_s: Optional[float] = None,
    extra_wall: Optional[dict] = None,
) -> dict:
    # sim_duration_s is the configured trace span; sim_advanced_s is the
    # virtual time the loop actually drove (clock.advanced), which exceeds it
    # whenever the request tail outlives the last arrival (slow boots, deep
    # queues). Speedup is computed from the driven time — the configured span
    # would understate it for long tails. Both are deterministic.
    driven = sim_advanced_s if sim_advanced_s is not None else sim_duration_s
    sim = {
        "scenario": name,
        "seed": seed,
        "sim_duration_s": round(sim_duration_s, 3),
        "sim_advanced_s": round(driven, 3),
        "pools": {p.cfg.name: pool_report(p) for p in fleet.pools.values()},
        "invariants": [iv.to_obj() for iv in invariants],
        "passed": all(iv.ok for iv in invariants),
    }
    if extra_sim:
        sim.update(extra_sim)
    wall = {
        "elapsed_s": round(wall_elapsed_s, 3),
        "sim_speedup": round(driven / max(wall_elapsed_s, 1e-9), 1),
        "pools": {
            p.cfg.name: pool_wall_report(p) for p in fleet.pools.values()
        },
    }
    # scenario-specific wall measurements (router-scale probes): host-
    # dependent like the rest of this section, excluded from determinism
    if extra_wall:
        wall.update(extra_wall)
    return {"sim": sim, "wall": wall}


def canonical_json(report: dict, include_wall: bool = False) -> str:
    """Byte-stable serialization of a report's deterministic section.

    Same seed + same scenario => identical string; the ``wall`` section
    (host-dependent latencies, elapsed time) is dropped unless asked for.
    """
    obj = report if include_wall else {
        k: v for k, v in report.items() if k != "wall"
    }
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def bench_record(reports: List[dict]) -> dict:
    """Fold scenario reports into the BENCH JSON schema (bench.py contract:
    one line, metric/value/unit/vs_baseline/detail). ``value`` is the
    fraction of closed-loop invariants holding across the suite;
    ``vs_baseline`` compares against all-pass (1.0), so any invariant
    regression reads as a perf verdict < 1 even with the TPU bench down."""
    invs = [iv for r in reports for iv in r["sim"]["invariants"]]
    ok = sum(1 for iv in invs if iv["ok"])
    frac = ok / max(len(invs), 1)
    decisions_us: List[float] = []
    ttft_p95 = {}
    itl_p95 = {}
    # fleet-wide KV reuse rollup (ISSUE: detail.global_cache in BENCH JSON):
    # hit rate comes from the directory-on scenario's own extra_sim (it
    # carries the counterfactual), block/dedupe counters fold across pools
    gcache: Dict[str, float] = {
        "fetched_blocks": 0, "recomputed_blocks": 0,
        "dedupe_skipped_blocks": 0, "hit_rate": 0.0,
        "hit_rate_local_counterfactual": 0.0, "dedupe_ratio": 0.0,
    }
    for r in reports:
        for w in r["wall"]["pools"].values():
            decisions_us.append(w["router_decision_us"]["p99"])
        for pname, p in r["sim"]["pools"].items():
            key = f'{r["sim"]["scenario"]}/{pname}'
            ttft_p95[key] = p["ttft"]["p95_ms"]
            itl_p95[key] = p["itl"]["p95_ms"]
            gc = p.get("global_cache")
            if gc:
                gcache["fetched_blocks"] += gc["fetched_blocks"]
                gcache["recomputed_blocks"] += gc["recomputed_blocks"]
                gcache["dedupe_skipped_blocks"] += gc["dedupe_skipped_blocks"]
                gcache["dedupe_ratio"] = max(
                    gcache["dedupe_ratio"], gc["dedupe_ratio"]
                )
        reuse = r["sim"].get("global_kv")
        if reuse:
            gcache["hit_rate"] = reuse["hit_rate_global"]
            gcache["hit_rate_local_counterfactual"] = reuse["hit_rate_local"]
    return {
        "metric": "sim_fleet_control_plane_gate",
        "value": round(frac, 4),
        "unit": "invariants_passed_fraction",
        "vs_baseline": round(frac, 4),
        "detail": {
            "scenarios": {
                r["sim"]["scenario"]: {
                    "passed": r["sim"]["passed"],
                    "seed": r["sim"]["seed"],
                    "sim_duration_s": r["sim"]["sim_duration_s"],
                    "wall_elapsed_s": r["wall"]["elapsed_s"],
                    "invariants": r["sim"]["invariants"],
                    "router_decision_us": {
                        pname: w["router_decision_us"]
                        for pname, w in r["wall"]["pools"].items()
                    },
                }
                for r in reports
            },
            "router_decision_p99_us_max": max(decisions_us) if decisions_us else 0.0,
            "sim_ttft_p95_ms": ttft_p95,
            "sim_itl_p95_ms": itl_p95,
            "global_cache": gcache,
        },
    }
