"""Deterministic virtual time: the sim's loop driver over the Clock funnel.

The injectable ``Clock`` base (and its live ``WALL`` instance) lives in
``runtime/clock.py`` so core modules never import from the sim package;
both are re-exported here for convenience. This module adds the virtual
half:

``VirtualClock`` + ``run()`` — a discrete-event driver over a *stock*
asyncio event loop. Rather than reimplementing timers, ``run()`` points
``loop.time`` at the virtual clock and wraps the loop's selector: whenever
the loop is about to block waiting for its earliest timer (i.e. no task is
runnable — the loop itself computed the idle gap), the wrapper advances
virtual time by exactly that gap instead of sleeping. Every
``asyncio.sleep`` / ``wait_for`` timeout on the loop thereby becomes a
virtual-time event with zero wall cost and zero host-scheduling jitter, so
a minutes-long diurnal trace replays in CI seconds and two same-seed runs
interleave identically (asyncio's ready queue and timer heap are FIFO /
(when, tiebreak-counter) ordered — deterministic given deterministic
inputs).

A sim that deadlocks (no runnable task, no pending timer) raises
``VirtualTimeStall`` instead of hanging CI: with wall I/O off the sim path,
a loop with nothing to run and nothing to wait for can never make progress.
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable, Union

from ..runtime.clock import WALL, Clock  # noqa: F401  (re-export)


class VirtualClock(Clock):
    """Virtual seconds; advanced only by the ``run()`` loop driver (or
    explicitly via ``advance`` in unit tests). ``sleep`` delegates to
    ``asyncio.sleep``, which is virtual under ``run()``."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self.advanced = 0.0  # total virtual seconds driven so far

    def time(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"cannot advance virtual time by {dt}")
        self._now += dt
        self.advanced += dt


class VirtualTimeStall(RuntimeError):
    """The virtualized loop has no runnable task and no pending timer."""


class _VirtualSelector:
    """Selector wrapper that converts idle blocking into time advancement.

    ``BaseEventLoop._run_once`` computes ``timeout`` as: 0 when callbacks
    are ready, ``earliest_timer - loop.time()`` when only timers pend, and
    None when nothing at all pends. We poll real FDs without blocking
    (call_soon_threadsafe self-pipe wakeups still work), and when the loop
    would have idled until a timer we jump the virtual clock there instead.
    """

    # consecutive no-timer no-event polls tolerated before declaring a stall
    # (a thread may be about to wake the loop via call_soon_threadsafe)
    _MAX_IDLE_POLLS = 3
    _IDLE_POLL_S = 0.05

    def __init__(self, inner, clock: VirtualClock):
        self._inner = inner
        self._clock = clock
        self._idle_polls = 0

    def select(self, timeout=None):
        events = self._inner.select(0)
        if events:
            self._idle_polls = 0
            return events
        if timeout is None:
            # no ready callbacks, no timers: either a thread is about to
            # wake us through the self-pipe (grace-poll for it) or the sim
            # is deadlocked
            self._idle_polls += 1
            if self._idle_polls > self._MAX_IDLE_POLLS:
                raise VirtualTimeStall(
                    "virtual-time deadlock: no runnable tasks and no timers "
                    "(a sim task is awaiting an event nothing will set)"
                )
            return self._inner.select(self._IDLE_POLL_S)
        self._idle_polls = 0
        if timeout > 0:
            self._clock.advance(timeout)
        return []

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def run(
    main: Union[Awaitable, Callable[[VirtualClock], Awaitable]],
    *,
    start: float = 0.0,
) -> Any:
    """Drive ``main`` to completion on a fresh virtual-time event loop.

    ``main`` is a coroutine, or a callable taking the ``VirtualClock`` and
    returning one (for code that wants the clock injected). Returns main's
    result; the loop (and any tasks it leaked) is torn down afterwards.
    """
    clock = VirtualClock(start)
    loop = asyncio.new_event_loop()
    inner = getattr(loop, "_selector", None)
    if inner is None:  # pragma: no cover - proactor/uvloop hosts
        loop.close()
        raise RuntimeError(
            "virtual time needs a selector event loop (loop._selector)"
        )
    loop._selector = _VirtualSelector(inner, clock)
    loop.time = clock.time  # type: ignore[method-assign]
    coro = main(clock) if callable(main) else main
    try:
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(coro)
    finally:
        try:
            leftovers = asyncio.all_tasks(loop)
            for t in leftovers:
                t.cancel()
            if leftovers:
                loop.run_until_complete(
                    asyncio.gather(*leftovers, return_exceptions=True)
                )
            loop.run_until_complete(loop.shutdown_asyncgens())
        finally:
            asyncio.set_event_loop(None)
            loop.close()
