"""Named fleet scenarios with machine-checked closed-loop invariants.

Each scenario wires a workload (sim/traces.py) through a SimFleet
(sim/fleet.py) on a virtual clock and asserts *control-plane properties* —
not point metrics but the loop behaviors ROADMAP item 3 needs proven:

- ``diurnal-autoscale``   planner tracks a diurnal load without oscillating
- ``bursty-breaker-chaos``  per-worker breakers trip on injected flaps,
                            steer traffic around them with bounded goodput
                            loss, and re-admit the worker after recovery
- ``prefix-heavy-radix``  KV routing keeps radix reuse high and queue
                            fairness intact under a hot shared-prefix group
- ``multi-pool-balance``  grid pool selection (global_router) splits SLA
                            classes onto the right pools and keeps the
                            interactive pool isolated from batch load
- ``multi-region-follow-sun``  phase-shifted regional diurnals keep the
                            combined fleet busy while each region holds SLA
- ``elastic-reclaim``     planned death of 30% of a warm fleet: drain,
                            mass KV evacuation, checkpoint, kill at the
                            deadline, warm restore — zero lost requests
                            (``-chaos`` variant drops the evacuation stream
                            and tears a checkpoint manifest)
- ``global-kv-reuse``     fleet-wide content-addressed KV directory: a hot
                            prefix split across two pools fetches from peer
                            tiers instead of re-prefilling; hit rate beats
                            the per-worker-radix counterfactual and a cold
                            worker's hot-prefix TTFT lands within 1.2x warm
- ``degradation-localization``  seeded mid-run slowdown of one worker's
                            step pacing + one wire's bandwidth: the
                            production detectors (runtime/health.py) name
                            the right worker and wire, the attribution
                            aggregator's p99 dominant phase flips to the
                            injected phase, and emissions stay rate-limited

Scenarios scale with ``workers`` and ``duration_s`` so the same invariants
run as a tier-1 smoke (small fleet, ~4 simulated minutes, seconds of wall
time) and as the full CLI gate (hundreds of workers, 10+ simulated
minutes). Every knob derives from (seed, workers, duration_s) only: same
inputs => byte-identical deterministic report section (sim/report.py).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..global_router.pool_selection import PrefillPoolSelectionStrategy
from ..profiler.loadgen import pct
from ..runtime.resilience import OPEN
from . import clock as simclock
from . import traces
from .fleet import FleetConfig, PoolConfig, SimFleet, worker_fault_point
from .report import Invariant, scenario_report

# per-worker mocker speed used by every scenario: slow enough that tens to
# hundreds of workers are *needed* at single-digit req/s rates (keeping the
# python step count — the wall cost — low), fast enough that a pool keeps
# its SLA with headroom. One worker sustains ~0.5 req/s of the default
# isl=256/osl=12 shape (measured; capacity_req_s below is the planner's
# profile of the same number).
_SPEED = dict(
    prefill_base_s=0.8,
    prefill_per_token_s=6.5e-3,
    decode_base_s=0.4,
    decode_per_kv_block_s=1e-5,
)
_CAPACITY_REQ_S = 0.3


def _invariant(name: str, ok: bool, detail: str) -> Invariant:
    return Invariant(name, bool(ok), detail)


def _slo_attainment(pool, sla_class: str, kind: str = "ttft") -> float:
    """Cumulative attainment from the pool's production ``SloAccountant``
    (sim/fleet.py feeds it per completed request on the virtual clock)."""
    att = pool.slo.attainment("sim", sla_class, window="total", kind=kind)
    return round(att, 4) if att is not None else 0.0


def _trace_ttft_attainment(pool) -> float:
    """The scenario-local math the accountant replaces — kept only as the
    agreement counterfactual for the mixed-SLA check."""
    done = [r for r in pool.records if r.ok]
    return round(
        sum(1 for r in done if r.ttft_s <= r.ttft_target_s)
        / max(len(done), 1), 4,
    )


# ---------------------------------------------------------------------------
# diurnal-autoscale
# ---------------------------------------------------------------------------


async def _diurnal_autoscale(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    periods = 2
    amplitude = 0.8
    peak_rate = 0.55 * workers * _CAPACITY_REQ_S
    mean_rate = peak_rate / (1 + amplitude)
    trace = traces.diurnal(
        duration_s=duration_s, mean_rate=mean_rate, amplitude=amplitude,
        period_s=duration_s / periods, isl=256, osl=12, seed=seed,
        # targets sized to the slow worker model: ~1.5s prefill + queueing
        # + up to 5s boot when a request lands on a just-spawned worker
        ttft_target_s=18.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[PoolConfig(
            name="decode", initial_workers=max(2, workers // 8),
            min_workers=1, max_workers=workers,
            autoscale=True, adjustment_interval_s=10.0,
            capacity_req_s=_CAPACITY_REQ_S, startup_time_s=5.0,
            scale_down_headroom=0.7,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import direction_flips, pool_report

    rep = pool_report(pool)
    replicas = [n for _, n in pool.replica_timeline]
    flips = direction_flips(replicas)
    peak = max(replicas) if replicas else 0
    final = replicas[-1] if replicas else 0
    invs = [
        _invariant(
            "scaled_up", peak >= max(3, int(0.35 * workers)),
            f"peak replicas {peak} (cap {workers})",
        ),
        _invariant(
            "scaled_back_down", final <= max(2, int(0.7 * peak)),
            f"final {final} vs peak {peak}",
        ),
        _invariant(
            "no_oscillation", flips <= 3 * periods,
            f"{flips} resize-direction flips over {periods} periods "
            f"(bound {3 * periods})",
        ),
        _invariant(
            "all_completed", rep["failed"] == 0,
            f'{rep["completed"]}/{rep["requests"]} completed',
        ),
        # re-derived from the production SloAccountant (runtime/slo.py) on
        # the virtual clock, not scenario-local percentile math
        _invariant(
            "ttft_sla_held", _slo_attainment(pool, "standard") >= 0.75,
            f'accountant ttft attainment '
            f'{_slo_attainment(pool, "standard")} (>= 0.75)',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# bursty-breaker-chaos
# ---------------------------------------------------------------------------


async def _bursty_breaker_chaos(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    flap_wid = 1  # first-spawned worker flaps
    flap_until = 0.6 * duration_s
    trace = traces.bursty(
        duration_s=duration_s,
        base_rate=0.15 * workers * _CAPACITY_REQ_S,
        burst_rate=0.9 * workers * _CAPACITY_REQ_S,
        burst_len_s=duration_s / 8, cycle_s=duration_s / 4,
        isl=256, osl=12, seed=seed, ttft_target_s=15.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5, max_attempts=4,
        # the flapping worker drops 95% of its dispatches on a seeded
        # schedule; a thin event-plane drop keeps the router view noisy too
        faults=(
            f"{worker_fault_point(flap_wid)}:drop@p=0.95@seed={seed + 17};"
            f"event_plane.publish:drop@p=0.02@seed={seed + 23}"
        ),
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=workers, max_workers=workers,
            breaker_threshold=3, breaker_window_s=60.0,
            breaker_reset_s=duration_s / 6,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()

    async def _recover() -> None:
        await clock.sleep(flap_until)
        fleet.disarm_fault(worker_fault_point(flap_wid))

    fleet.spawn_task(_recover())
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import pool_report

    rep = pool_report(pool)
    opens = [t for t, wid, st in pool.breaker_events
             if wid == flap_wid and st == OPEN]
    first_open = opens[0] if opens else float("inf")
    done = [r for r in pool.records if r.ok]
    during = [r for r in done if first_open <= r.t_arrive <= flap_until]
    on_flapped = sum(1 for r in during if r.worker == flap_wid)
    share_during = on_flapped / max(len(during), 1)
    fair = 1.0 / workers
    after = [r for r in done
             if r.t_arrive > flap_until + pool.cfg.breaker_reset_s]
    recovered = sum(1 for r in after if r.worker == flap_wid)
    goodput = rep["completed"] / max(rep["requests"], 1)
    invs = [
        _invariant(
            "breaker_tripped", bool(opens),
            f"worker {flap_wid} breaker opened at t={opens[:3]}",
        ),
        _invariant(
            "goodput_held", goodput >= 0.99,
            f"goodput {goodput:.4f} with {rep['retries']} retries "
            "(retry-then-migrate absorbs the flap)",
        ),
        _invariant(
            "steered_around", share_during <= 0.5 * fair,
            f"flapping worker served {share_during:.4f} of traffic while "
            f"tripped (fair share {fair:.4f})",
        ),
        _invariant(
            "recovered_after_flap", recovered >= 1,
            f"worker {flap_wid} served {recovered} requests after recovery",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# prefix-heavy-radix
# ---------------------------------------------------------------------------


async def _prefix_heavy_radix(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    num_groups = max(4, workers)
    # run the fleet at ~60% utilization: saturated workers would make the
    # scheduler's load term rightly override radix affinity, which is the
    # steady-state this scenario is NOT about
    trace = traces.prefix_heavy(
        duration_s=duration_s, rate=0.35 * workers * _CAPACITY_REQ_S,
        isl=256, osl=12, num_groups=num_groups, hot_group_share=0.4,
        seed=seed, ttft_target_s=10.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.75,
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=workers, max_workers=workers,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import pool_report

    rep = pool_report(pool)
    done = [r for r in pool.records if r.ok]
    by_group: Dict[int, List] = {}
    for r in done:
        by_group.setdefault(r.group, []).append(r)
    # radix routing's per-request effect: the engine confirmed (via
    # cached_tokens on the first output) that the chosen worker already
    # held most of the shared prefix. Group members may legitimately span
    # several workers — the scheduler *replicates* a hot prefix when its
    # holders are loaded — so the property is reuse-on-arrival, not
    # single-worker affinity.
    shared_len = 0.75 * 256
    prefix_routed = sum(
        1 for r in done if r.cached_tokens >= 0.75 * shared_len
    ) / max(len(done), 1)
    # fairness: cold groups must not starve behind the hot group
    cold_attain = [
        sum(1 for r in rs if r.ttft_s <= r.ttft_target_s) / len(rs)
        for g, rs in sorted(by_group.items()) if g != 0 and len(rs) >= 10
    ]
    worst_cold = min(cold_attain) if cold_attain else 1.0
    used_workers = {r.worker for r in done}
    invs = [
        _invariant(
            "radix_reuse", rep["cache_hit_ratio"] >= 0.4,
            f'cache hit ratio {rep["cache_hit_ratio"]} '
            "(0.75 of each group prompt is shared)",
        ),
        _invariant(
            "prefix_routed", prefix_routed >= 0.7,
            f"{prefix_routed:.3f} of requests landed on a worker already "
            "holding >=75% of their shared prefix",
        ),
        _invariant(
            "queue_fairness", worst_cold >= 0.6,
            f"worst cold-group TTFT attainment {worst_cold:.3f} "
            "(hot group must not starve the rest)",
        ),
        _invariant(
            "fleet_spread", len(used_workers) >= max(2, int(0.75 * workers)),
            f"{len(used_workers)}/{workers} workers served traffic",
        ),
        _invariant(
            "all_completed", rep["failed"] == 0,
            f'{rep["completed"]}/{rep["requests"]} completed',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# multi-pool-balance
# ---------------------------------------------------------------------------


async def _multi_pool_balance(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    w_inter = max(2, workers // 2)
    w_batch = max(2, workers - w_inter)
    classes = [
        {"name": "interactive", "weight": 0.65, "isl": 128, "osl": 8,
         "ttft_target_s": 8.0, "itl_target_s": 3.0},
        {"name": "batch", "weight": 0.35, "isl": 1024, "osl": 24,
         "ttft_target_s": 60.0, "itl_target_s": 3.0},
    ]
    # interactive pool is sized for short prompts; batch pool absorbs the
    # heavy ISL class (its per-request cost is ~8x the interactive one)
    rate = 0.55 * w_inter * _CAPACITY_REQ_S / classes[0]["weight"] * 0.5
    trace = traces.sla_classes(
        duration_s=duration_s, rate=rate, classes=classes, seed=seed,
    )
    # the real global_router grid: (ISL, TTFT target) -> pool index
    strategy = PrefillPoolSelectionStrategy(
        ttft_min=0.0, ttft_max=60.0, ttft_resolution=2,
        isl_min=0, isl_max=2048, isl_resolution=2,
        prefill_pool_mapping=[[0, 0], [1, 1]],
    )
    pool_names = ["interactive", "batch"]
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[
            PoolConfig(
                name="interactive", namespace="sim-inter",
                initial_workers=w_inter, min_workers=w_inter,
                max_workers=w_inter, **_SPEED,
            ),
            PoolConfig(
                name="batch", namespace="sim-batch",
                initial_workers=w_batch, min_workers=w_batch,
                max_workers=w_batch, **_SPEED,
            ),
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()

    def pool_for(sreq: traces.SimRequest) -> str:
        idx = strategy.select_pool(sreq.item.isl, sreq.ttft_target_s)
        return pool_names[idx]

    try:
        await fleet.run_trace(trace, pool_for=pool_for)
    finally:
        await fleet.stop()

    from .report import pool_report

    inter, batch = fleet.pools["interactive"], fleet.pools["batch"]
    rep_i, rep_b = pool_report(inter), pool_report(batch)
    misrouted = (
        sum(1 for r in inter.records if r.isl >= 1024)
        + sum(1 for r in batch.records if r.isl < 1024)
    )
    # in-pool balance: no worker hoards traffic
    def max_share(rep: dict) -> float:
        counts = list(rep["per_worker_requests"].values())
        return max(counts) / max(sum(counts), 1) if counts else 0.0

    fair_i = 1.0 / w_inter
    invs = [
        _invariant(
            "selection_correct", misrouted == 0,
            f"{misrouted} requests landed in the wrong pool "
            "(grid: isl<1024 -> interactive)",
        ),
        _invariant(
            "all_completed", rep_i["failed"] == 0 and rep_b["failed"] == 0,
            f'interactive {rep_i["completed"]}/{rep_i["requests"]}, '
            f'batch {rep_b["completed"]}/{rep_b["requests"]}',
        ),
        _invariant(
            "interactive_isolated", rep_i["ttft_attainment"] >= 0.9,
            f'interactive TTFT attainment {rep_i["ttft_attainment"]} '
            "despite batch-class load on the fleet",
        ),
        _invariant(
            "in_pool_balance", max_share(rep_i) <= 3.0 * fair_i,
            f"hottest interactive worker share {max_share(rep_i):.3f} "
            f"(fair {fair_i:.3f})",
        ),
        # mixed-SLA-classes accounting: the production SloAccountant's
        # per-class ledger must (a) hold the interactive promise and (b)
        # agree exactly with the trace-derived attainment — proving the
        # accountant code path on deterministic virtual time
        _invariant(
            "mixed_sla_classes_accounted",
            _slo_attainment(inter, "interactive") >= 0.9
            and _slo_attainment(inter, "interactive")
            == _trace_ttft_attainment(inter)
            and _slo_attainment(batch, "batch")
            == _trace_ttft_attainment(batch),
            f'accountant interactive {_slo_attainment(inter, "interactive")} '
            f'(trace {_trace_ttft_attainment(inter)}), '
            f'batch {_slo_attainment(batch, "batch")} '
            f'(trace {_trace_ttft_attainment(batch)})',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# multi-region-follow-sun
# ---------------------------------------------------------------------------


async def _multi_region_follow_sun(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    regions = 2
    per_region = max(2, workers // regions)
    amplitude = 0.8
    peak_rate = 0.5 * per_region * _CAPACITY_REQ_S
    region_traces = traces.multi_region(
        regions=regions, duration_s=duration_s,
        mean_rate=peak_rate / (1 + amplitude), amplitude=amplitude,
        isl=256, osl=12, seed=seed, ttft_target_s=12.0, itl_target_s=3.0,
    )
    trace = traces.merge(*region_traces.values())
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[
            PoolConfig(
                name=f"r{i}", namespace=f"sim-r{i}",
                initial_workers=per_region, min_workers=per_region,
                max_workers=per_region, **_SPEED,
            )
            for i in range(regions)
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace, pool_for=lambda sr: sr.region)
    finally:
        await fleet.stop()

    from .report import pool_report

    reps = {name: pool_report(p) for name, p in fleet.pools.items()}
    # per-region attainment from each pool's production SloAccountant
    # (was scenario-local percentile math before the slo plane landed)
    attains = {name: _slo_attainment(p, "standard")
               for name, p in fleet.pools.items()}
    counts = {name: r["requests"] for name, r in reps.items()}
    total = sum(counts.values())
    shares = {n: c / max(total, 1) for n, c in counts.items()}
    invs = [
        _invariant(
            "regions_balanced",
            max(shares.values()) - min(shares.values()) <= 0.15,
            f"request shares {shares} (phase-shifted peaks, near-even total)",
        ),
        _invariant(
            "all_regions_hold_sla", min(attains.values()) >= 0.75,
            f"per-region TTFT attainment {attains}",
        ),
        _invariant(
            "all_completed",
            all(r["failed"] == 0 for r in reps.values()),
            f"completed per region {dict((n, r['completed']) for n, r in reps.items())}",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# disagg-streamed-prefill
# ---------------------------------------------------------------------------


async def _disagg_streamed_prefill(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """Disaggregated prefill/decode with the REAL PrefillRouter in the loop
    (ROADMAP item 3 remainder): every arrival is planned by
    ``PrefillRouter.plan`` — transfer-cost-aware candidate scoring over the
    prefill pool's real KvRouter, short-prompt/radix/load deflection — then
    the prefill leg runs on a mocker prefill pool and the decode leg on the
    decode pool, with the wire modeled per request by the deterministic
    ``ops.costs.streamed_transfer_model`` at the scenario's per-worker wire
    classes. Invariants gate the PR 10 acceptance criteria: streamed TTFT
    <= the blocking counterfactual, deflection active under the load mix,
    cost-aware steering toward fast-wire workers, and disagg TTFT within
    1.15x of an equal-capacity colocated twin fleet on the same trace."""
    import asyncio

    from ..llm.model_card import ModelDeploymentCard
    from ..llm.prefill_router import DisaggConfig, PrefillRouter
    from ..llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from ..ops.costs import streamed_transfer_model
    from ..profiler.loadgen import prefix_prompt
    from ..runtime.bandwidth import WireBandwidthEstimator
    from ..runtime.engine import Context
    from .traces import SimRequest, TraceItem

    block_size = 16
    prefill_chunk = 512
    kv_bytes_per_block = 2 << 20            # a ~70B-class bf16 block
    speed = dict(_SPEED, prefill_base_s=0.2)
    # wire classes per prefill worker: even ids sit a native hop away, odd
    # ids only reach the decode pool over a congested inline path — the
    # skew the cost-aware router must price
    wire_priors = {"native": 2.0e9, "inline": 1.0e8}

    p_workers = max(2, workers // 2)
    d_workers = max(2, workers - p_workers)
    long_isl, short_isl, osl = 2048, 48, 12
    long_w = 0.65
    prefill_cost_long = speed["prefill_base_s"] + speed["prefill_per_token_s"] * long_isl
    rate = 0.35 * p_workers / (long_w * prefill_cost_long)
    classes = [
        {"weight": 1 - long_w, "isl": short_isl, "osl": osl,
         "ttft_target_s": 10.0, "itl_target_s": 3.0},
        {"weight": long_w, "isl": long_isl, "osl": osl,
         "ttft_target_s": 30.0, "itl_target_s": 3.0},
    ]
    trace = traces.sla_classes(
        duration_s=duration_s, rate=rate, classes=classes, seed=seed,
    )

    dcfg = DisaggConfig(
        streamed=True, deflect=True,
        deflect_max_tokens=64, deflect_overlap_frac=0.5, deflect_margin=2.0,
        prefill_block_time_s=speed["prefill_per_token_s"] * block_size,
        kv_bytes_per_block=kv_bytes_per_block,
    )

    # ---- phase 1: disagg fleet (decode pool + prefill pool) ----------------
    cfg = FleetConfig(
        seed=seed, prefix_share=0.0,
        pools=[
            PoolConfig(
                name="decode", namespace="sim-dec",
                initial_workers=d_workers, min_workers=d_workers,
                max_workers=d_workers, block_size=block_size, **speed,
            ),
            PoolConfig(
                name="prefill", namespace="sim-pre",
                initial_workers=p_workers, min_workers=p_workers,
                max_workers=p_workers, block_size=block_size, **speed,
            ),
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    decode_pool = fleet.pools["decode"]
    prefill_pool = fleet.pools["prefill"]

    p_wids = sorted(prefill_pool.workers)
    wires = {
        wid: ("native" if i < (len(p_wids) + 1) // 2 else "inline")
        for i, wid in enumerate(p_wids)
    }

    class _Inst:
        def __init__(self, wid: int):
            self.metadata = {
                "data_parallel_size": 1,
                "transfer_address": f"sim://prefill/{wid}",
                "kv_wire": wires[wid],
            }

    class _StubClient:
        """The real Client surface PrefillRouter.plan reads."""

        @property
        def instances(self):
            return {wid: _Inst(wid) for wid in sorted(prefill_pool.workers)}

    prefill_card = ModelDeploymentCard(
        name="sim", component="prefill", kv_block_size=block_size,
    )
    router = PrefillRouter(runtime=None, card=prefill_card, disagg=dcfg)
    router.client = _StubClient()
    router.kv_router = prefill_pool.router        # the REAL prefill KvRouter
    router.bandwidth = WireBandwidthEstimator(priors=wire_priors)

    streamed_ttfts: List[float] = []
    blocking_ttfts: List[float] = []
    deflect_reasons: Dict[str, int] = {}
    disagg_wires: List[str] = []
    failures = [0]

    async def _prefill_leg(wid: int, rid: str, tokens: List[int]) -> float:
        w = prefill_pool.workers.get(wid)
        if w is None:  # retired between plan and dispatch: any worker
            w = next(iter(prefill_pool.workers.values()))
        req = PreprocessedRequest(
            request_id=rid, model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=1, min_tokens=1, ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )
        t0 = clock.time()
        async for out in w.engine.generate(req, Context(rid)):
            if out.finish_reason is not None:
                break
        return clock.time() - t0

    async def _one(idx: int, sreq: SimRequest) -> None:
        item = sreq.item
        t_arr = clock.time()
        tokens = prefix_prompt(item, idx, fleet.cfg.prefix_share)
        preq = PreprocessedRequest(
            request_id=f"sim-disagg-{idx}", model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=item.osl),
            sampling=SamplingOptions(temperature=0.0),
        )
        overlap = decode_pool.router.score_tokens(
            tokens, decode_pool._candidates()
        ).overlap_blocks if decode_pool.workers else 0
        plan = router.plan(preq, decode_overlap_blocks=overlap)
        if plan is None or plan.deflected:
            reason = plan.deflect_reason if plan is not None else "no_candidates"
            deflect_reasons[reason] = deflect_reasons.get(reason, 0) + 1
            rec = await decode_pool.submit(idx, sreq)
            if not rec.ok:
                failures[0] += 1
                return
            streamed_ttfts.append(rec.ttft_s)
            blocking_ttfts.append(rec.ttft_s)  # no wire either way
            return
        disagg_wires.append(plan.wire)
        prefill_s = await _prefill_leg(
            plan.worker_id, f"{preq.request_id}.p", tokens
        )
        chunks = max(-(-item.isl // prefill_chunk), 1)
        model = streamed_transfer_model(
            item.isl,
            block_size=block_size,
            prefill_chunk=prefill_chunk,
            kv_bytes_per_block=kv_bytes_per_block,
            bandwidth_bytes_s=router.bandwidth.bandwidth(plan.wire),
            prefill_chunk_s=prefill_s / chunks,
            window_blocks=8,
        )
        streamed_extra = max(model["streamed_ttft_s"] - model["prefill_s"], 0.0)
        blocking_extra = max(model["blocking_ttft_s"] - model["prefill_s"], 0.0)
        router.bandwidth.observe(plan.wire, model["bytes"], model["transfer_s"])
        if streamed_extra > 0:
            await clock.sleep(streamed_extra)  # the un-hidden wire tail
        # decode leg: the transferred prefix is resident; only the final
        # partial block's tokens are recomputed on the decode worker
        tail = item.isl % block_size or block_size
        tail_req = SimRequest(
            TraceItem(item.t, tail, item.osl, item.group),
            ttft_target_s=sreq.ttft_target_s, itl_target_s=sreq.itl_target_s,
            region=sreq.region,
        )
        t_submit = clock.time()
        rec = await decode_pool.submit(idx, tail_req, tokens=tokens[-tail:])
        if not rec.ok:
            failures[0] += 1
            return
        ttft = (t_submit - t_arr) + rec.ttft_s
        streamed_ttfts.append(ttft)
        blocking_ttfts.append(ttft + (blocking_extra - streamed_extra))

    try:
        tasks: List[asyncio.Task] = []
        t_prev = 0.0
        for idx, sreq in enumerate(trace):
            dt = sreq.t - t_prev
            t_prev = sreq.t
            if dt > 0:
                await clock.sleep(dt)
            tasks.append(asyncio.create_task(_one(idx, sreq)))
        if tasks:
            await asyncio.gather(*tasks)
    finally:
        await fleet.stop()

    # ---- phase 2: colocated twin (equal capacity, same trace) --------------
    colo_cfg = FleetConfig(
        seed=seed, prefix_share=0.0,
        pools=[PoolConfig(
            name="colocated", namespace="sim-colo",
            initial_workers=d_workers + p_workers,
            min_workers=d_workers + p_workers,
            max_workers=d_workers + p_workers,
            block_size=block_size, **speed,
        )],
    )
    colo = SimFleet(colo_cfg, clock)
    await colo.start()
    try:
        await colo.run_trace(trace)
    finally:
        await colo.stop()

    from ..profiler.loadgen import pct

    colo_ttfts = sorted(
        r.ttft_s for r in colo.pools["colocated"].records if r.ok
    )
    s_sorted = sorted(streamed_ttfts)
    b_sorted = sorted(blocking_ttfts)
    p50_s, p50_b = pct(s_sorted, 0.5), pct(b_sorted, 0.5)
    mean_s = sum(s_sorted) / max(len(s_sorted), 1)
    mean_b = sum(b_sorted) / max(len(b_sorted), 1)
    p50_colo = pct(colo_ttfts, 0.5)
    n_total = len(trace)
    n_deflected = sum(deflect_reasons.values())
    share = n_deflected / max(n_total, 1)
    fast_share = (
        sum(1 for w in disagg_wires if w == "native") / len(disagg_wires)
        if disagg_wires else 0.0
    )
    colo_failed = sum(1 for r in colo.pools["colocated"].records if not r.ok)
    invs = [
        _invariant(
            "streamed_le_blocking",
            p50_s <= p50_b and (not disagg_wires or mean_s < mean_b),
            f"streamed TTFT p50 {p50_s:.3f}s mean {mean_s:.3f}s vs blocking "
            f"counterfactual p50 {p50_b:.3f}s mean {mean_b:.3f}s "
            f"({len(disagg_wires)} disagg requests)",
        ),
        _invariant(
            "deflection_active",
            0.15 <= share <= 0.85 and deflect_reasons.get("short_prompt", 0) > 0,
            f"deflected {n_deflected}/{n_total} ({share:.3f}) by reason "
            f"{dict(sorted(deflect_reasons.items()))}",
        ),
        _invariant(
            "wire_cost_steering", fast_share >= 0.55,
            f"{fast_share:.3f} of disagg prefills landed on native-wire "
            "workers (half the pool; cost-blind routing would give ~0.5)",
        ),
        _invariant(
            "near_colocated_ttft", p50_s <= 1.15 * p50_colo,
            f"disagg TTFT p50 {p50_s:.3f}s vs colocated {p50_colo:.3f}s "
            f"(bound 1.15x = {1.15 * p50_colo:.3f}s)",
        ),
        _invariant(
            "all_completed", failures[0] == 0 and colo_failed == 0,
            f"disagg failures {failures[0]}, colocated failures {colo_failed}",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# router-scale-sublinear
# ---------------------------------------------------------------------------


def _probe_decision_latency(pool, trace, n: int = 400) -> Dict[str, list]:
    """Wall-clock routing-decision probe on the post-trace router state:
    ``score_tokens`` (side-effect-free) over trace-shaped prompts, pruned
    (the configured top-K) and exact (top-K forced to 0, the linear scan).
    Per-call wall ns lists — host-dependent, wall-section only. Each
    prompt is measured twice and the per-prompt MIN kept, so a one-off GC
    pause or scheduler hiccup on a loaded host cannot inflate the p99 the
    sublinearity invariant reads."""
    from ..profiler.loadgen import prefix_prompt

    router = pool.router
    prompts = [
        prefix_prompt(trace[i % len(trace)].item, i,
                      pool.fleet.cfg.prefix_share)
        for i in range(n)
    ]

    def run(topk: int) -> list:
        saved = router.config.topk_candidates
        router.config.topk_candidates = topk
        try:
            for toks in prompts[:20]:  # warm caches/allocator
                router.score_tokens(toks)
            lat = [float("inf")] * len(prompts)
            for _pass in range(2):
                for i, toks in enumerate(prompts):
                    t0 = time.perf_counter_ns()
                    router.score_tokens(toks)
                    lat[i] = min(lat[i], time.perf_counter_ns() - t0)
        finally:
            router.config.topk_candidates = saved
        return lat

    return {
        "pruned_ns": run(router.config.topk_candidates or 16),
        "exact_ns": run(0),
    }


def _ns_pcts(ns: list) -> Dict[str, float]:
    xs = sorted(ns)
    return {
        "p50_us": round(pct(xs, 0.50) / 1e3, 1),
        "p99_us": round(pct(xs, 0.99) / 1e3, 1),
    }


async def _router_scale(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """Control-plane scale (ROADMAP item: 10k workers): the SAME
    prefix-heavy trace shape runs against a small and a large mocker fleet
    behind the real KvRouter — candidate-free routing over the registered
    universe, pruned top-K decisions by default. The wall section records
    decision-latency p50/p99 at both sizes plus a pruned-vs-exact probe;
    the headline invariant is sublinearity: the large fleet's p99 within
    3x the small fleet's (the linear scan scales ~size-ratio x). Like
    ``http-frontend``, the latency invariants derive from WALL
    measurements (floored and noise-trimmed), so this scenario asserts
    bounded behavior and is deliberately absent from the byte-identity
    pins; everything else in its sim section stays seed-deterministic."""
    large = max(workers, 512)
    small = max(64, large // 8)
    rate = 3.0  # FIXED across sizes: the trace shape must be identical

    phases: Dict[str, Dict] = {}
    for label, size in (("small", small), ("large", large)):
        trace = traces.prefix_heavy(
            duration_s=duration_s, rate=rate, isl=256, osl=8,
            num_groups=48, hot_group_share=0.4, seed=seed,
            ttft_target_s=30.0, itl_target_s=5.0,
        )
        cfg = FleetConfig(
            seed=seed, prefix_share=0.75,
            pools=[PoolConfig(
                name=label, namespace=f"sim-scale-{label}",
                initial_workers=size, min_workers=size, max_workers=size,
                num_blocks=512, **_SPEED,
            )],
        )
        fleet = SimFleet(cfg, clock)
        await fleet.start()
        try:
            await fleet.run_trace(trace)
        finally:
            await fleet.stop()
        pool = fleet.pools[label]
        # live-decision counters BEFORE the probe pollutes them: the trace's
        # own decisions are the deterministic prune-share evidence
        counters = {
            "pruned_decisions": pool.router.pruned_decisions,
            "exact_decisions": pool.router.exact_decisions,
        }
        phases[label] = {
            "size": size,
            "fleet": fleet,
            "pool": pool,
            "counters": counters,
            "probe": _probe_decision_latency(pool, trace),
            "requests": len(trace),
        }

    from .report import pool_report

    sm, lg = phases["small"], phases["large"]
    probe = {
        label: {
            "fleet_size": ph["size"],
            "pruned": _ns_pcts(ph["probe"]["pruned_ns"]),
            "exact": _ns_pcts(ph["probe"]["exact_ns"]),
        }
        for label, ph in phases.items()
    }
    p99_small = probe["small"]["pruned"]["p99_us"]
    p99_large = probe["large"]["pruned"]["p99_us"]
    p50_small = probe["small"]["pruned"]["p50_us"]
    p50_large = probe["large"]["pruned"]["p50_us"]
    # floors guard the ratio against sub-20us denominators on fast hosts
    ok_p99 = p99_large <= 3.0 * max(p99_small, 20.0)
    ok_p50 = p50_large <= 3.0 * max(p50_small, 10.0)
    exact_p50_large = probe["large"]["exact"]["p50_us"]
    rep_s, rep_l = pool_report(sm["pool"]), pool_report(lg["pool"])
    lg_total = (
        lg["counters"]["pruned_decisions"] + lg["counters"]["exact_decisions"]
    )
    pruned_share = lg["counters"]["pruned_decisions"] / max(lg_total, 1)
    size_ratio = lg["size"] / sm["size"]
    invs = [
        _invariant(
            "decision_p99_sublinear", ok_p99,
            f"pruned decision p99 {p99_large}us at {lg['size']} workers vs "
            f"{p99_small}us at {sm['size']} (bound 3x for a {size_ratio:.0f}x "
            "fleet; the linear scan scales with the fleet)",
        ),
        _invariant(
            "decision_p50_sublinear", ok_p50,
            f"pruned decision p50 {p50_large}us at {lg['size']} workers vs "
            f"{p50_small}us at {sm['size']} (bound 3x)",
        ),
        _invariant(
            "pruned_beats_exact_at_scale",
            p50_large < exact_p50_large,
            f"pruned p50 {p50_large}us vs exact linear-scan p50 "
            f"{exact_p50_large}us at {lg['size']} workers",
        ),
        _invariant(
            "pruned_is_default_path", pruned_share >= 0.9,
            f"{lg['counters']['pruned_decisions']}/{lg_total} live decisions "
            "took the pruned path at the large fleet",
        ),
        _invariant(
            "radix_reuse_at_scale", rep_l["cache_hit_ratio"] >= 0.35,
            f'large-fleet cache hit ratio {rep_l["cache_hit_ratio"]} '
            "(pruned prefix candidates must keep finding the holders)",
        ),
        _invariant(
            "all_completed",
            rep_s["failed"] == 0 and rep_l["failed"] == 0,
            f'small {rep_s["completed"]}/{rep_s["requests"]}, '
            f'large {rep_l["completed"]}/{rep_l["requests"]}',
        ),
    ]
    return {
        "fleet": lg["fleet"],
        "invariants": invs,
        "requests": lg["requests"],
        "extra_sim": {
            "scale": {
                label: {
                    "fleet_size": ph["size"],
                    "completed": pool_report(ph["pool"])["completed"],
                    "cache_hit_ratio": pool_report(ph["pool"])["cache_hit_ratio"],
                    **ph["counters"],
                }
                for label, ph in phases.items()
            },
        },
        "extra_wall": {
            "router_probe": probe,
            "small_fleet_decision_us": (
                _ns_pcts(sm["pool"].decision_wall_ns)
            ),
        },
    }


# ---------------------------------------------------------------------------
# http-frontend
# ---------------------------------------------------------------------------


async def _http_frontend(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """The REAL HTTP frontend in the virtual-clock loop (the last sim
    realism gap): a real aiohttp ``HttpService`` on a localhost socket, a
    real KV-mode ``ModelPipeline`` (preprocessor -> Migration -> per-worker
    breakers -> KvRouter) over the mocker fleet, driven by a real aiohttp
    client. Bursts overrun ``busy_threshold`` so admission sheds with 503s;
    a seeded flap on one worker trips its frontend-side breaker so routing
    steers around it and Migration absorbs the losses; /metrics, /debug/slo
    and /debug/fleet are scraped over the wire — the fleet fan-out against
    one worker with a REAL StatusServer, one advertising a dead address,
    and the rest advertising nothing, so the partial-result merge (stale
    entries, never a 500) is exercised over live sockets. Socket readiness
    is real I/O, so this scenario's counts are *bounded*, not
    byte-deterministic — its invariants assert behavior windows, and it is
    deliberately absent from the byte-identity pins."""
    import os

    import aiohttp

    from ..llm.discovery import ModelManager, ModelPipeline
    from ..llm.http.service import HttpService
    from ..llm.model_card import ModelDeploymentCard
    from ..llm.protocols.common import PreprocessedRequest
    from ..runtime.component import RouterMode
    from ..runtime.config import ENV_FLEET_TIMEOUT_S
    from ..runtime.faults import FAULTS, FaultInjected
    from ..runtime.health import HealthState, StatusServer

    flap_wid = 1
    flap_until = 0.55 * duration_s
    busy_threshold = max(6, 2 * workers)
    trace = traces.bursty(
        duration_s=duration_s,
        base_rate=0.2 * workers * _CAPACITY_REQ_S,
        burst_rate=1.1 * workers * _CAPACITY_REQ_S,
        burst_len_s=duration_s / 8, cycle_s=duration_s / 4,
        isl=128, osl=6, seed=seed, ttft_target_s=60.0, itl_target_s=5.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        faults=f"sim.http.worker.{flap_wid}:drop@p=0.9@seed={seed + 31}",
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=workers, max_workers=workers, **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    pool = fleet.default_pool

    serve_log: List[tuple] = []    # (t, wid) engine dispatches that started
    fault_log: List[tuple] = []    # (t, wid) flap-injected connection losses
    calls = [0]

    class _Inst:
        __slots__ = ("metadata",)

        def __init__(self, extra=None):
            self.metadata = {"data_parallel_size": 1, **(extra or {})}

    # per-worker discovery metadata: the /debug/fleet fan-out reads each
    # instance's advertised status_address (engine/__main__.py stamps it
    # after the side port binds); populated once the live StatusServer is up
    status_meta: Dict[int, Dict] = {}

    class _Stream:
        """Worker stream with the ``instance_id`` tag Migration attributes
        failures to (the request plane's _TaggedStream analog)."""

        def __init__(self, gen, iid):
            self._gen = gen.__aiter__()
            self.instance_id = iid

        def __aiter__(self):
            return self

        def __anext__(self):
            return self._gen.__anext__()

    class _SimClient:
        """The Client surface ModelPipeline reads, over the mocker fleet."""

        @property
        def instances(self):
            return {
                wid: _Inst(status_meta.get(wid)) for wid in pool.workers
            }

        def instance_ids(self):
            return list(pool.workers)

        async def generate(self, obj, context, instance_id=None):
            calls[0] += 1
            w = pool.workers.get(instance_id)
            if w is None:
                e = ConnectionError(f"sim worker {instance_id} gone")
                e.instance_id = instance_id
                raise e
            try:
                # drop raises InjectedDrop (a ConnectionError) so it looks
                # like transport loss; fail raises FaultInjected
                await FAULTS.ainject(f"sim.http.worker.{instance_id}")
            except (FaultInjected, ConnectionError) as flap:
                fault_log.append((clock.time(), instance_id))
                e = ConnectionError(str(flap))
                e.instance_id = instance_id
                raise e
            serve_log.append((clock.time(), instance_id))
            req = PreprocessedRequest.from_obj(obj)
            return _Stream(w.engine.generate(req, context), instance_id)

    card = ModelDeploymentCard(
        name="sim-http", tokenizer="byte", context_length=8192,
        kv_block_size=pool.cfg.block_size, migration_limit=3,
    )
    pipeline = ModelPipeline(None, card, RouterMode.KV)
    pipeline.client = _SimClient()
    pipeline.kv_router = pool.router  # the pool's REAL KvRouter
    manager = ModelManager()
    manager.add("sim-http", pipeline)
    service = HttpService(
        manager, busy_threshold=busy_threshold, host="127.0.0.1", port=0,
    )
    await service.start()
    base = f"http://127.0.0.1:{service.port}"

    # one worker backs its advertised status_address with a REAL
    # StatusServer (its /debug/worker document feeds the merge rollups),
    # one advertises a dead address (connection refused -> stale entry),
    # the rest advertise nothing (stale: "no status_address advertised")
    wids = sorted(pool.workers)
    live_wid = wids[len(wids) // 2]
    dead_wid = wids[-1]

    def _worker_doc() -> Dict:
        w = pool.workers.get(live_wid)
        active = len(w.engine.kv.active) if w is not None else 0
        total = pool.cfg.num_blocks
        return {
            "worker": f"sim-{live_wid}",
            "kv": {
                "active_blocks": active,
                "free_blocks": total - active,
                "total_blocks": total,
            },
            "restore_mode": "warm",
            "health": {"active": []},
        }

    status = StatusServer(
        HealthState(), host="127.0.0.1", port=0,
        worker_snapshot_fn=_worker_doc,
    )
    status_addr = await status.start()
    status_meta[live_wid] = {"status_address": status_addr}
    status_meta[dead_wid] = {"status_address": "127.0.0.1:1"}
    # the fan-out's per-worker timeout is judged on the virtualized loop
    # clock, which can jump while a real TCP exchange is in flight — widen
    # it so only genuinely dead addresses go stale
    prev_timeout = os.environ.get(ENV_FLEET_TIMEOUT_S)
    os.environ[ENV_FLEET_TIMEOUT_S] = "600"

    # a steady timer keeps the virtualized selector polling (socket
    # readiness is real I/O the loop must keep observing) and bounds how
    # far virtual time can jump while a TCP exchange is in flight
    async def _heartbeat():
        while True:
            await clock.sleep(0.2)

    fleet.spawn_task(_heartbeat())

    async def _recover():
        await clock.sleep(flap_until)
        fleet.disarm_fault(f"sim.http.worker.{flap_wid}")

    fleet.spawn_task(_recover())

    # frontend-side breaker transitions for the flapping worker, sampled on
    # the virtual clock (discovery builds these lazily per worker id)
    breaker_states: List[tuple] = []

    async def _monitor():
        last = None
        while True:
            await clock.sleep(1.0)
            cb = pipeline._worker_breakers.get(flap_wid)
            st = cb.state if cb is not None else "unknown"
            if st != last:
                breaker_states.append((round(clock.time(), 1), st))
                last = st

    fleet.spawn_task(_monitor())

    statuses: Dict[str, int] = {}

    def _note(key: str) -> None:
        statuses[key] = statuses.get(key, 0) + 1

    results = {"ok": 0, "failed": 0, "client_retries": 0}
    timeout = aiohttp.ClientTimeout(
        total=None, connect=None, sock_read=None, sock_connect=None
    )
    session = aiohttp.ClientSession(
        timeout=timeout, connector=aiohttp.TCPConnector(force_close=True),
    )

    async def _one(idx: int, sreq: traces.SimRequest) -> None:
        item = sreq.item
        shared = (f"g{item.group % 100:02d}:" * item.isl)[: int(item.isl * 0.6)]
        text = (shared + f"u{idx}:" * item.isl)[: item.isl]
        body = {
            "model": "sim-http", "prompt": text,
            "max_tokens": item.osl, "stream": False,
        }
        for attempt in range(8):
            if attempt:
                results["client_retries"] += 1
            try:
                async with session.post(
                    base + "/v1/completions", json=body
                ) as resp:
                    status = resp.status
                    try:
                        data = await resp.json()
                    except Exception:
                        data = None
            except aiohttp.ClientError:
                _note("conn_error")
                await clock.sleep(1.0)
                continue
            if status == 200:
                _note("200")
                results["ok"] += 1
                return
            if status == 503:
                msg = ((data or {}).get("error") or {}).get("message", "")
                busy = "busy" in msg
                _note("503_busy" if busy else "503_circuit")
                try:
                    retry_after = float(resp.headers.get("Retry-After", 1.0))
                except ValueError:
                    retry_after = 1.0
                # linear backoff past the burst tail: shed load must come
                # back later, not hammer the breaker window
                await clock.sleep(min(retry_after, 2.0) + 2.0 * attempt + 0.5)
                continue
            _note(str(status))
            break
        results["failed"] += 1

    metrics_text = ""
    slo_payload: Dict = {}
    fleet_payload: Dict = {}
    fleet_status = 0
    try:
        import asyncio

        tasks: List = []
        t_prev = 0.0
        for idx, sreq in enumerate(trace):
            dt = sreq.t - t_prev
            t_prev = sreq.t
            if dt > 0:
                await clock.sleep(dt)
            tasks.append(asyncio.create_task(_one(idx, sreq)))
        if tasks:
            await asyncio.gather(*tasks)
        # scrape the observability surfaces over the real wire
        async with session.get(base + "/metrics") as r:
            if r.status == 200:
                metrics_text = await r.text()
        async with session.get(base + "/debug/slo") as r:
            if r.status == 200:
                slo_payload = await r.json()
        async with session.get(base + "/debug/fleet") as r:
            fleet_status = r.status
            if r.status == 200:
                fleet_payload = await r.json()
    finally:
        if prev_timeout is None:
            os.environ.pop(ENV_FLEET_TIMEOUT_S, None)
        else:
            os.environ[ENV_FLEET_TIMEOUT_S] = prev_timeout
        await session.close()
        await status.stop()
        await service.stop()
        await fleet.stop()

    n_req = len(trace)
    goodput = results["ok"] / max(n_req, 1)
    opens = [t for t, st in breaker_states if st == OPEN]
    first_open = opens[0] if opens else float("inf")
    during = [
        (t, wid) for t, wid in serve_log if first_open <= t <= flap_until
    ]
    on_flapped = sum(1 for _, wid in during if wid == flap_wid)
    share_during = on_flapped / max(len(during), 1)
    fair = 1.0 / workers
    shed = statuses.get("503_busy", 0)
    invs = [
        _invariant(
            "admission_shed", shed > 0 and shed < n_req,
            f"frontend shed {shed} requests with busy-503 at "
            f"busy_threshold={busy_threshold} (statuses "
            f"{dict(sorted(statuses.items()))})",
        ),
        _invariant(
            "breaker_steered",
            bool(opens) and share_during <= max(0.5 * fair, 0.02),
            f"worker {flap_wid} breaker opened at t={opens[:3]}; it served "
            f"{share_during:.4f} of dispatches while tripped "
            f"(fair share {fair:.4f})",
        ),
        _invariant(
            "migration_absorbed",
            len(fault_log) >= 3 and goodput >= 0.97,
            f"{len(fault_log)} injected worker losses absorbed "
            f"(retry-then-migrate); goodput {goodput:.4f} over {n_req}",
        ),
        _invariant(
            "frontend_observable",
            "dtpu_requests_total" in metrics_text
            and "sim-http" in str(slo_payload.get("models", {})),
            "/metrics exposes dtpu_requests_total and /debug/slo carries "
            "the sim-http ledger, scraped over the live socket",
        ),
        _invariant(
            "fleet_snapshot_partial",
            fleet_status == 200
            and fleet_payload.get("fleet", {}).get("workers_total")
            == workers
            and fleet_payload.get("fleet", {}).get("workers_live") == 1
            and fleet_payload.get("fleet", {}).get("workers_stale")
            == workers - 1
            and "attribution" in fleet_payload.get("frontend", {}),
            f"/debug/fleet answered {fleet_status} over the live socket "
            f"with {workers} workers (1 live via a real StatusServer, "
            f"{workers - 1} stale: one dead address, the rest "
            f"unadvertised) — partial results never turn into a 500; "
            f"fleet rollup: {fleet_payload.get('fleet')}",
        ),
    ]
    return {
        "fleet": fleet,
        "invariants": invs,
        "requests": n_req,
        "extra_sim": {
            "http": {
                "statuses": dict(sorted(statuses.items())),
                "client_retries": results["client_retries"],
                "generate_calls": calls[0],
                "breaker_transitions": breaker_states,
                "fleet_snapshot": {
                    "status": fleet_status,
                    "rollup": fleet_payload.get("fleet"),
                    "restore_modes": fleet_payload.get("restore_modes"),
                    "merged_kv": fleet_payload.get("kv"),
                },
            },
        },
    }


# ---------------------------------------------------------------------------
# elastic-reclaim
# ---------------------------------------------------------------------------


async def _elastic_reclaim_impl(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float,
    chaos: bool,
) -> Dict:
    """Planned worker death at fleet scale (docs/operations.md §13): 30% of
    a loaded, radix-warm fleet receives a reclaim notice with a 30s virtual
    deadline. Drained workers leave routing immediately, short in-flight
    decodes run out, sealed KV bulk-evacuates to bandwidth-priced
    destinations, the REAL engine/checkpoint.py writer snapshots each
    victim, the kill fires at the deadline (still-running decodes migrate),
    and replacements restore WARM from the checkpoints. Invariants: zero
    lost requests, goodput >= 0.97, restored-worker first-token TTFT within
    1.2x a never-killed warm worker's, draining workers never receive new
    traffic, and cost-priced evacuation steers to fast-wire destinations.
    The chaos variant drops the evacuation stream mid-window (the
    block-window protocol resumes per block) and fails one checkpoint
    mid-manifest (restore detects the partial checkpoint and cold-boots) —
    still with zero lost requests."""
    import asyncio
    import os
    import shutil
    import tempfile

    from ..llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from ..profiler.loadgen import prefix_prompt
    from ..runtime.engine import Context

    workers = max(4, workers)
    n_victims = max(1, int(round(0.3 * workers)))
    deadline_s = 30.0
    t_drain = 0.45 * duration_s
    share = 0.75
    trace = traces.prefix_heavy(
        duration_s=duration_s, rate=0.35 * workers * _CAPACITY_REQ_S,
        isl=256, osl=12, num_groups=max(4, workers), hot_group_share=0.4,
        seed=seed, ttft_target_s=15.0, itl_target_s=3.0,
    )
    # long decodes that outlive the notice window, arriving just before it:
    # the quiesce wait cannot finish them, so the deadline kill cuts them
    # mid-decode and the submit loop must migrate them (the "long ones
    # bulk-migrate" half of the drain contract)
    long_osl = int((deadline_s + 20.0) / _SPEED["decode_base_s"])
    trace = traces.merge(trace, [
        traces.SimRequest(
            traces.TraceItem(t_drain - 6.0 + 0.5 * j, 64, long_osl, 900 + j),
            ttft_target_s=60.0, itl_target_s=3.0,
        )
        for j in range(workers)
    ])
    faults = ""
    if chaos:
        faults = (
            f"transfer.stream_window:drop@p=0.4@seed={seed + 41};"
            "checkpoint.manifest:fail@1"
        )
    cfg = FleetConfig(
        seed=seed, prefix_share=share, max_attempts=4, faults=faults,
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=1, max_workers=2 * workers,
            startup_time_s=5.0, **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    pool = fleet.default_pool
    # victims are picked at notice time, not up front: a real reclaim does
    # not politely choose idle machines, so we take the BUSIEST workers of
    # the original fleet — guaranteeing in-flight decodes at the deadline
    victims: List[int] = []
    ckpt_root = tempfile.mkdtemp(prefix="dtpu-sim-ckpt-")
    drains: List[Dict] = []
    restores: List[Dict] = []

    async def _reclaim() -> None:
        await clock.sleep(t_drain)
        cands = [wid for wid in sorted(pool.workers) if wid <= workers]
        cands.sort(key=lambda wid: (
            -(pool.workers[wid].engine.snapshot()["running"]
              + pool.workers[wid].engine.snapshot()["waiting"]),
            wid,
        ))
        victims.extend(cands[:n_victims])
        outs = await asyncio.gather(*[
            pool.drain_worker(
                wid, deadline_s=deadline_s,
                ckpt_dir=os.path.join(ckpt_root, f"w{wid}"),
            )
            for wid in victims
        ])
        drains.extend(outs)
        for wid in victims:
            restores.append(
                await pool.restore_worker(os.path.join(ckpt_root, f"w{wid}"))
            )

    async def _probe_ttft(engine, rid: str, tokens: List[int]) -> float:
        req = PreprocessedRequest(
            request_id=rid, model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=1, min_tokens=1, ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )
        t0 = clock.time()
        async for out in engine.generate(req, Context(rid)):
            if out.finish_reason is not None:
                break
        return clock.time() - t0

    def _replay_tokens(wid: int) -> Optional[List[int]]:
        """The prompt of a request this worker completed while alive — its
        blocks are exactly what a warm cache (or a restored checkpoint of
        one) must hold."""
        for r in pool.records:
            if r.ok and r.worker == wid and r.idx < len(trace):
                return prefix_prompt(trace[r.idx].item, r.idx, share)
        return None

    restored_ttfts: List[float] = []
    baseline_ttft = 0.0
    reclaim_task = asyncio.create_task(_reclaim())
    try:
        await fleet.run_trace(trace)
        await reclaim_task
        # first-token probes against live engines, before teardown: each
        # restored replacement replays a prompt ITS victim served (warmth
        # must come from the checkpoint's pre-seeded pages), the baseline
        # is a never-killed survivor replaying a prompt it served itself
        survivors = [wid for wid in pool.workers if wid not in victims
                     and wid <= workers]
        base_wid = max(
            survivors, key=lambda wid: (pool.workers[wid].requests, -wid)
        )
        base_toks = _replay_tokens(base_wid)
        if base_toks is not None:
            baseline_ttft = await _probe_ttft(
                pool.workers[base_wid].engine, "probe-warm", base_toks
            )
        for i, (vic, r) in enumerate(zip(victims, restores)):
            if r["mode"] != "warm":
                continue
            w = pool.workers.get(r["wid"])
            toks = _replay_tokens(vic)
            if w is None or toks is None:
                continue
            restored_ttfts.append(
                await _probe_ttft(w.engine, f"probe-restored-{i}", toks)
            )
    finally:
        reclaim_task.cancel()
        await asyncio.gather(reclaim_task, return_exceptions=True)
        await fleet.stop()
        shutil.rmtree(ckpt_root, ignore_errors=True)

    from .report import pool_report

    rep = pool_report(pool)
    goodput = rep["completed"] / max(rep["requests"], 1)
    killed_in_flight = sum(d.get("killed_in_flight", 0) for d in drains)
    victim_set = set(victims)
    routed_to_draining = sum(
        1 for r in pool.records
        if r.worker in victim_set and r.t_arrive > t_drain + 0.5
    )
    native_share = (
        sum(1 for wr in pool.evac_dest_wires if wr == "native")
        / max(len(pool.evac_dest_wires), 1)
    )
    modes = sorted(r["mode"] for r in restores)
    worst_ratio = (
        max(restored_ttfts) / baseline_ttft
        if restored_ttfts and baseline_ttft > 0 else float("inf")
    )
    invs = [
        _invariant(
            "zero_lost_requests", rep["failed"] == 0,
            f'{rep["completed"]}/{rep["requests"]} completed; '
            f"{killed_in_flight} in flight at the kills, all migrated "
            f'({rep["retries"]} retries)',
        ),
        _invariant(
            "goodput_held", goodput >= 0.97,
            f"goodput {goodput:.4f} through a 30% planned fleet loss",
        ),
        _invariant(
            "long_decodes_migrated", killed_in_flight >= 1,
            f"the deadline kill cut {killed_in_flight} still-running "
            "request(s); the retry loop re-ran them elsewhere",
        ),
        _invariant(
            "draining_excluded", routed_to_draining == 0,
            f"{routed_to_draining} new arrivals routed to a draining worker "
            f"after the notice at t={t_drain:.0f}s",
        ),
        _invariant(
            "kv_evacuated",
            pool.evacuated_blocks_total > 0 and native_share >= 0.6,
            f"{pool.evacuated_blocks_total} sealed blocks evacuated; "
            f"{native_share:.3f} of windows steered to native-wire "
            "destinations (cost-priced, not round-robin; half the pool)",
        ),
        _invariant(
            "deadline_respected",
            all(d.get("margin_s", -1.0) >= 0.0 for d in drains),
            f"checkpoint margins {[d.get('margin_s') for d in drains]}s "
            f"before the {deadline_s:.0f}s deadline",
        ),
    ]
    if chaos:
        resumed = sum(d.get("resumed_windows", 0) for d in drains)
        ckpt_failed = sum(
            1 for d in drains if str(d.get("ckpt", "")).startswith("failed")
        )
        cold = sum(1 for m in modes if m == "cold")
        invs += [
            _invariant(
                "stream_drops_resumed", resumed > 0,
                f"{resumed} evacuation windows dropped mid-stream and "
                "resumed per block (no block lost)",
            ),
            _invariant(
                "partial_checkpoint_cold_boot",
                ckpt_failed == 1 and cold == ckpt_failed
                and len(modes) == len(victims),
                f"{ckpt_failed} checkpoint(s) died mid-manifest commit; "
                f"restore modes {modes} (partial checkpoints detected, "
                "cold-booted; the rest restored warm)",
            ),
        ]
    else:
        invs += [
            _invariant(
                "restored_warm", modes == ["warm"] * len(victims),
                f"restore modes {modes} over {len(victims)} replacements",
            ),
            _invariant(
                "warm_restore_ttft", worst_ratio <= 1.2,
                f"restored first-token TTFT worst ratio {worst_ratio:.3f} "
                f"vs never-killed warm worker {baseline_ttft:.3f}s "
                "(bound 1.2x)",
            ),
        ]
    return {
        "fleet": fleet,
        "invariants": invs,
        "requests": len(trace),
        "extra_sim": {
            "reclaim": {
                "victims": victims,
                "drains": drains,
                "restores": restores,
                "restored_ttft_s": [round(t, 4) for t in restored_ttfts],
                "baseline_ttft_s": round(baseline_ttft, 4),
                "native_wire_share": round(native_share, 4),
            },
        },
    }


async def _elastic_reclaim(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    return await _elastic_reclaim_impl(clock, seed, workers, duration_s, False)


async def _elastic_reclaim_chaos(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    return await _elastic_reclaim_impl(clock, seed, workers, duration_s, True)


# ---------------------------------------------------------------------------
# global-kv-reuse
# ---------------------------------------------------------------------------


async def _global_kv_reuse(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """Fleet-wide KV reuse over the content-addressed directory
    (kvbm/directory.py): a prefix-heavy trace alternates across two pools,
    so the SAME hot group's prefix is needed in both — per-pool radix alone
    cannot warm the second pool. With the directory on, a local radix miss
    prices onboard-from-peer-tier vs recompute (ops/costs.fetch_vs_recompute
    on the tier-wire EWMA) and fetches the longest single-holder run; the
    identical trace replays with the directory OFF as the per-worker-radix
    counterfactual. Invariants: fleet-wide hit rate strictly beats the
    counterfactual, a cold worker's TTFT on the fleet-hot prefix (wire time
    included) lands within 1.2x a warm worker's, zero failed requests in
    both runs, fetches actually happen, and dedupe bounds hot-prefix
    advertisements to the configured holder count."""
    from ..llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from ..profiler.loadgen import prefix_prompt
    from ..runtime.engine import Context
    from ..tokens import compute_sequence_hashes

    share = 0.75
    block_size = 16

    def _mk_trace() -> List[traces.SimRequest]:
        return traces.prefix_heavy(
            duration_s=duration_s, rate=0.35 * workers * _CAPACITY_REQ_S,
            isl=256, osl=8, num_groups=max(4, workers),
            hot_group_share=0.5, seed=seed,
            ttft_target_s=18.0, itl_target_s=3.0,
        )

    def _mk_fleet(enabled: bool) -> SimFleet:
        half = max(1, workers // 2)
        return SimFleet(FleetConfig(
            seed=seed, prefix_share=share, max_attempts=3,
            global_kv=enabled,
            pools=[
                PoolConfig(name="east", initial_workers=half,
                           block_size=block_size, **_SPEED),
                PoolConfig(name="west", initial_workers=half,
                           block_size=block_size, **_SPEED),
            ],
        ), clock)

    def _mk_pool_for() -> Callable:
        flip = {"n": 0}

        def pool_for(sreq) -> str:
            # alternate arrivals across pools: every group's prefix is hot
            # in BOTH pools, which only fleet-level reuse can exploit
            flip["n"] += 1
            return "east" if flip["n"] % 2 else "west"

        return pool_for

    def _hit_rate(fl: SimFleet) -> float:
        cached = inputs = 0
        for pool in fl.pools.values():
            for r in pool.records:
                if r.ok:
                    cached += r.cached_tokens
                    inputs += r.input_tokens
        return cached / max(inputs, 1)

    def _failed(fl: SimFleet) -> int:
        return sum(
            sum(1 for r in pool.records if not r.ok)
            for pool in fl.pools.values()
        )

    async def _probe_ttft(engine, rid: str, tokens: List[int]) -> float:
        req = PreprocessedRequest(
            request_id=rid, model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=1, min_tokens=1, ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )
        t0 = clock.time()
        async for out in engine.generate(req, Context(rid)):
            if out.finish_reason is not None:
                break
        return clock.time() - t0

    # ---- the directory-on run + cold/warm probes ----
    fleet = _mk_fleet(True)
    await fleet.start()
    warm_ttft = cold_ttft = 0.0
    cold_seeded_blocks = 0
    try:
        await fleet.run_trace(_mk_trace(), pool_for=_mk_pool_for())
        east = fleet.pools["east"]
        west = fleet.pools["west"]
        # the fleet-hot prefix: group 0's shared tokens, truncated to whole
        # blocks so every probed token sits in a sealed (advertised) page
        hot = next(
            (r for r in east.records
             if r.ok and r.group == 0 and r.worker in east.workers), None,
        )
        if hot is not None:
            trace_items = _mk_trace()
            n_shared = (int(256 * share) // block_size) * block_size
            probe_toks = prefix_prompt(
                trace_items[hot.idx].item, hot.idx, share
            )[:n_shared]
            # warm: the worker that served the request replays its prefix
            warm_ttft = await _probe_ttft(
                east.workers[hot.worker].engine, "probe-warm", probe_toks
            )
            # cold: a brand-new worker in the OTHER pool — its only path to
            # warmth is a directory lookup + peer-tier fetch, and the wire
            # time is charged to its TTFT
            wid_cold = west._spawn(startup_s=0.0)
            w_cold = west.workers[wid_cold]
            t0 = clock.time()
            await west._global_fetch(wid_cold, w_cold, probe_toks)
            fetch_s = clock.time() - t0
            cold_seeded_blocks = w_cold.engine.kv.cached_prefix_len(
                compute_sequence_hashes(probe_toks, block_size)
            )
            cold_ttft = fetch_s + await _probe_ttft(
                w_cold.engine, "probe-cold", probe_toks
            )
        hit_global = _hit_rate(fleet)
        failed_on = _failed(fleet)
        fetched = sum(p.global_fetched_blocks for p in fleet.pools.values())
        recomputed = sum(
            p.global_recomputed_blocks for p in fleet.pools.values()
        )
        fetch_events = sum(
            p.global_fetch_events for p in fleet.pools.values()
        )
        stale = sum(p.global_stale_skips for p in fleet.pools.values())
        dedupe = sum(
            d.dedupe_skipped
            for p in fleet.pools.values() for d in p._dirs.values()
        )
    finally:
        await fleet.stop()

    # ---- the per-worker-radix counterfactual: same trace, directory off ----
    twin = _mk_fleet(False)
    await twin.start()
    try:
        await twin.run_trace(_mk_trace(), pool_for=_mk_pool_for())
        hit_local = _hit_rate(twin)
        failed_off = _failed(twin)
    finally:
        await twin.stop()

    ratio = cold_ttft / warm_ttft if warm_ttft > 0 else float("inf")
    invs = [
        _invariant(
            "fleet_hit_beats_local_radix", hit_global > hit_local,
            f"fleet-wide hit rate {hit_global:.4f} vs per-worker radix "
            f"counterfactual {hit_local:.4f} on the same trace",
        ),
        _invariant(
            "cold_hot_prefix_ttft", ratio <= 1.2,
            f"cold-worker TTFT on the fleet-hot prefix {cold_ttft:.3f}s "
            f"(incl. fetch wire time) vs warm {warm_ttft:.3f}s — "
            f"ratio {ratio:.3f} (bound 1.2x; {cold_seeded_blocks} blocks "
            "onboarded from a peer tier)",
        ),
        _invariant(
            "zero_failed_requests", failed_on == 0 and failed_off == 0,
            f"failed: {failed_on} with the directory on, {failed_off} in "
            "the counterfactual",
        ),
        _invariant(
            "fetch_path_active", fetch_events > 0 and fetched > 0,
            f"{fetch_events} peer-tier fetches onboarded {fetched} blocks "
            f"({recomputed} recomputed, {stale} stale-holder fallbacks)",
        ),
        _invariant(
            "dedupe_bounded_holders", dedupe > 0,
            f"{dedupe} hot-prefix publishes skipped at the configured "
            "holder bound (identical sealed blocks dedupe fleet-wide)",
        ),
    ]
    return {
        "fleet": fleet,
        "invariants": invs,
        "requests": sum(
            len(p.records) for p in fleet.pools.values()
        ),
        "extra_sim": {
            "global_kv": {
                "hit_rate_global": round(hit_global, 4),
                "hit_rate_local": round(hit_local, 4),
                "cold_ttft_s": round(cold_ttft, 4),
                "warm_ttft_s": round(warm_ttft, 4),
                "cold_warm_ratio": round(ratio, 4),
                "fetched_blocks": fetched,
                "recomputed_blocks": recomputed,
                "dedupe_skipped_blocks": dedupe,
                "stale_holder_skips": stale,
            },
        },
    }


# ---------------------------------------------------------------------------
# degradation-localization
# ---------------------------------------------------------------------------


async def _degradation_localization(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """The observability plane catching a seeded fault it was never told
    about: halfway through a steady prefill-heavy trace, ONE worker's step
    pacing slows 8x and the ``inline`` transfer wire collapses 20x. The
    PRODUCTION detectors (runtime/health.py HealthMonitor) and attribution
    aggregator (runtime/attribution.py) run on the virtual clock over the
    live fleet signals — the scenario never tells them which worker or
    wire it broke. Invariants: ``cost_model_drift`` fires and every firing
    names exactly the slowed worker; ``wire_collapse`` fires and names
    exactly the collapsed wire; the aggregator's p99 dominant phase flips
    from ``prefill_compute`` (the healthy prefill-heavy trace) to
    ``decode`` (the injected slowdown's phase); emissions respect the
    rate limit and hysteresis (zero events before the injection, zero
    spurious recoveries); zero failed requests. Fully deterministic:
    same (seed, workers, duration_s) => byte-identical report section."""
    from ..runtime.attribution import AttributionAggregator
    from ..runtime.bandwidth import WireBandwidthEstimator
    from ..runtime.flight_recorder import FlightRecorder
    from ..runtime.health import HealthMonitor

    # a large slowdown, deliberately: one degraded request's decode must
    # outweigh the phase sums of the handful of healthy stragglers that
    # share the p99 tail with it, at any fleet scale
    slow_factor = 30.0
    wire_factor = 20.0
    inject_at = duration_s / 2.0
    tick_s = 2.0
    min_interval_s = 20.0
    # steady arrivals at low utilization: healthy requests almost never
    # queue, so the healthy p99 tail stays compute-shaped
    trace = traces.diurnal(
        duration_s=duration_s, mean_rate=0.2 * workers * _CAPACITY_REQ_S,
        amplitude=0.0, period_s=duration_s, isl=512, osl=5,
        num_groups=max(4, workers), seed=seed,
        ttft_target_s=60.0, itl_target_s=15.0,
    )
    fleet = SimFleet(FleetConfig(
        seed=seed, prefix_share=0.25,
        pools=[PoolConfig(
            name="serve", initial_workers=workers,
            min_workers=workers, max_workers=workers,
            # one sequence per worker at a time: ITL gaps are then pure
            # decode pacing (no co-running prefill chunks riding in the
            # iteration), so the healthy p99 tail stays prefill-dominant
            # and the flip invariant isolates the injected slowdown.
            # Pure least-loaded routing (no radix affinity): the worker-
            # reported load (active + waiting_prefill_blocks) steers
            # arrivals off the backed-up slow worker, so its queue —
            # whose wait would land in prefill_queue and mask the decode
            # flip — never forms; the slow worker still takes work when
            # idle, which is exactly the degraded-but-unqueued stream the
            # tail should surface. The staleness horizon must outlast the
            # slowed worker's publish cadence (one step = slow_factor x
            # decode_base), else it scores as idle between its steps.
            max_num_seqs=1, overlap_weight=0.0, router_stale_s=30.0,
            **_SPEED,
        )],
    ), clock)
    await fleet.start()
    pool = fleet.default_pool
    slow_wid = sorted(pool.workers)[len(pool.workers) // 2]
    slow_subject = f"worker/{slow_wid}"

    # the production observability plane, on the virtual clock; a local
    # flight recorder keeps health timelines out of the process global
    monitor = HealthMonitor(
        clock=clock.time, min_interval_s=min_interval_s, drift_ratio=2.0,
        flight_recorder=FlightRecorder(),
    )
    events: List = []
    sub = monitor.subscribe(events.append)
    agg = AttributionAggregator(clock=clock.time)
    est = WireBandwidthEstimator()
    healthy_bw = est.bandwidth("inline")  # the static prior
    wire_down = [False]
    base_decode_s = _SPEED["decode_base_s"]
    drained = [0]

    last_finish: Dict[int, float] = {}

    def _drain_records() -> None:
        """Feed completed requests to the aggregator through the same
        attribute() path the frontends use, on a synthetic timeline built
        from the record's measured milestones. Workers serve one request
        at a time here, so the engine-admission milestone the production
        flight recorder would stamp is reconstructible: a request is
        admitted when its predecessor on the same worker finished — queue
        wait then lands in prefill_queue (as in production timelines)
        instead of polluting prefill_compute."""
        recs = pool.records
        while drained[0] < len(recs):
            rec = recs[drained[0]]
            drained[0] += 1
            if not rec.ok or rec.ttft_s < 0:
                continue
            finish_s = rec.t_arrive + rec.ttft_s + rec.itl_sum_s
            admitted_s = min(
                max(rec.t_arrive, last_finish.get(rec.worker, 0.0)),
                rec.t_arrive + rec.ttft_s,
            )
            last_finish[rec.worker] = finish_s
            t0 = int(rec.t_arrive * 1e9)
            t_adm = int(admitted_s * 1e9)
            t_ft = t0 + int(rec.ttft_s * 1e9)
            t_end = t_ft + int(rec.itl_sum_s * 1e9)
            agg.observe_flight("sim", rec.sla_class, {"events": [
                {"timestamp": t0, "event": {"kind": "received"}},
                {"timestamp": t0, "event": {"kind": "queued"}},
                {"timestamp": t_adm, "event": {"kind": "admitted"}},
                {"timestamp": t_ft, "event": {"kind": "first_token"}},
                {"timestamp": t_end, "event": {"kind": "finish"}},
            ]})

    async def _ticker() -> None:
        # the sampling loop a worker's step hook / transfer client replace
        # in production: measured pacing vs the cost model's prediction,
        # and the wire EWMA vs its own history
        while True:
            await clock.sleep(tick_s)
            for wid, w in sorted(pool.workers.items()):
                monitor.observe_step(
                    f"worker/{wid}", w.engine.perf.decode_base_s,
                    base_decode_s,
                )
            nbytes = 1 << 20
            bw = healthy_bw / (wire_factor if wire_down[0] else 1.0)
            est.observe("inline", nbytes, nbytes / bw)
            monitor.observe_wire("inline", est.bandwidth("inline"))
            _drain_records()

    snap_before: Dict = {}

    async def _inject() -> None:
        await clock.sleep(inject_at)
        _drain_records()
        snap_before.update(agg.snapshot())
        # the seeded fault: pacing drifts on ONE worker (the mocker's perf
        # constants ARE its virtual step durations), one wire collapses
        pool.workers[slow_wid].engine.perf.decode_base_s *= slow_factor
        wire_down[0] = True

    fleet.spawn_task(_ticker())
    fleet.spawn_task(_inject())
    try:
        await fleet.run_trace(trace)
        # let stragglers finish and the detectors settle
        await clock.sleep(30.0)
        _drain_records()
        snap_after = agg.snapshot()
    finally:
        sub.close()
        await fleet.stop()

    def _p99_dominant(snap: Dict, window: str) -> Optional[str]:
        classes = snap.get("models", {}).get("sim", {})
        body = next(iter(classes.values()), {}).get(window, {})
        return (body.get("p99") or {}).get("dominant")

    degraded = [e for e in events if e.kind == "degraded"]
    recovered = [e for e in events if e.kind == "recovered"]
    drift = [e for e in degraded if e.detector == "cost_model_drift"]
    wire = [e for e in degraded if e.detector == "wire_collapse"]
    false_pos = [e for e in degraded if e.t < inject_at]
    dom_before = _p99_dominant(snap_before, "total")
    dom_after = _p99_dominant(snap_after, "total")
    failed = sum(1 for r in pool.records if not r.ok)

    def _spaced(evs: List) -> bool:
        ts = [e.t for e in evs]
        return all(b - a >= min_interval_s - 1e-6
                   for a, b in zip(ts, ts[1:]))

    # ceiling on per-subject emissions over the degraded window (trace
    # tail + straggler completions + the settling sleep): the trip plus
    # min_interval-spaced re-emissions
    last_t = max((e.t for e in degraded), default=inject_at)
    max_emits = 1 + int((last_t - inject_at) / min_interval_s)
    invs = [
        _invariant(
            "drift_localized",
            bool(drift) and all(e.subject == slow_subject for e in drift),
            f"cost_model_drift fired {len(drift)}x, subjects "
            f"{sorted({e.subject for e in drift})} (injected: "
            f"{slow_subject} slowed {slow_factor}x at t={inject_at:.0f})",
        ),
        _invariant(
            "wire_localized",
            bool(wire) and all(e.subject == "wire/inline" for e in wire),
            f"wire_collapse fired {len(wire)}x, subjects "
            f"{sorted({e.subject for e in wire})} (injected: wire/inline "
            f"collapsed {wire_factor}x)",
        ),
        _invariant(
            "p99_dominant_flip",
            dom_before != "decode" and dom_after == "decode",
            f"p99 dominant phase {dom_before} at injection -> {dom_after} "
            "after (the injected slowdown lands in decode)",
        ),
        _invariant(
            "rate_limited_no_flap",
            not false_pos and not recovered
            and len(drift) <= max_emits and len(wire) <= max_emits
            and _spaced(drift) and _spaced(wire),
            f"{len(false_pos)} events before injection, {len(recovered)} "
            f"spurious recoveries; {len(drift)}/{len(wire)} emissions "
            f"within the {max_emits}-emission rate-limit ceiling, spaced "
            f">= {min_interval_s:.0f}s",
        ),
        _invariant(
            "zero_failed_requests", failed == 0,
            f"{failed} failed requests under the injected degradation",
        ),
    ]
    return {
        "fleet": fleet,
        "invariants": invs,
        "requests": len(trace),
        "extra_sim": {
            "degradation": {
                "slow_worker": slow_wid,
                "injected_at_s": round(inject_at, 3),
                "drift_events": len(drift),
                "wire_events": len(wire),
                "first_drift_t": round(drift[0].t, 3) if drift else None,
                "first_wire_t": round(wire[0].t, 3) if wire else None,
                "dominant_before": dom_before,
                "dominant_after": dom_after,
            },
        },
    }


# ---------------------------------------------------------------------------
# registry + runner
# ---------------------------------------------------------------------------

SCENARIOS: Dict[str, Callable] = {
    "diurnal-autoscale": _diurnal_autoscale,
    "bursty-breaker-chaos": _bursty_breaker_chaos,
    "prefix-heavy-radix": _prefix_heavy_radix,
    "multi-pool-balance": _multi_pool_balance,
    "multi-region-follow-sun": _multi_region_follow_sun,
    "disagg-streamed-prefill": _disagg_streamed_prefill,
    "router-scale-sublinear": _router_scale,
    "http-frontend": _http_frontend,
    "elastic-reclaim": _elastic_reclaim,
    "elastic-reclaim-chaos": _elastic_reclaim_chaos,
    "global-kv-reuse": _global_kv_reuse,
    "degradation-localization": _degradation_localization,
}

# aliases accepted by the CLI (`python -m dynamo_tpu.sim diurnal`)
ALIASES = {
    "diurnal": "diurnal-autoscale",
    "bursty": "bursty-breaker-chaos",
    "prefix": "prefix-heavy-radix",
    "multipool": "multi-pool-balance",
    "regions": "multi-region-follow-sun",
    "disagg": "disagg-streamed-prefill",
    "scale": "router-scale-sublinear",
    "frontend": "http-frontend",
    "reclaim": "elastic-reclaim",
    "reclaim-chaos": "elastic-reclaim-chaos",
    "globalkv": "global-kv-reuse",
    "degradation": "degradation-localization",
}


def resolve(name: str) -> str:
    full = ALIASES.get(name, name)
    if full not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)} "
            f"(aliases {sorted(ALIASES)})"
        )
    return full


def run_scenario(
    name: str,
    seed: int = 0,
    workers: int = 8,
    duration_s: Optional[float] = None,
) -> dict:
    """Run one scenario to completion on a fresh virtual-time loop and
    return its report (sim/report.py schema). Blocking; call from sync
    code (CLI, bench.py, tests)."""
    full = resolve(name)
    duration = float(duration_s) if duration_s is not None else 240.0
    t0 = time.perf_counter()

    async def main(clock: simclock.VirtualClock):
        return await SCENARIOS[full](clock, seed, workers, duration), clock

    out, clock = simclock.run(main)
    return scenario_report(
        name=full, seed=seed, fleet=out["fleet"],
        invariants=out["invariants"], sim_duration_s=duration,
        wall_elapsed_s=time.perf_counter() - t0,
        extra_sim={
            "workers": workers, "trace_requests": out["requests"],
            **out.get("extra_sim", {}),
        },
        sim_advanced_s=clock.advanced,
        extra_wall=out.get("extra_wall"),
    )


def run_suite(
    names: Optional[List[str]] = None,
    seed: int = 0,
    workers: int = 8,
    duration_s: Optional[float] = None,
) -> List[dict]:
    """The perf-gate suite: the four gate scenarios (plus any extras asked
    for) at the given scale."""
    gate = names or [
        "diurnal-autoscale", "bursty-breaker-chaos",
        "prefix-heavy-radix", "multi-pool-balance",
        "disagg-streamed-prefill", "router-scale-sublinear",
        "http-frontend", "elastic-reclaim", "global-kv-reuse",
        "degradation-localization",
    ]
    return [
        run_scenario(n, seed=seed, workers=workers, duration_s=duration_s)
        for n in gate
    ]
