"""Named fleet scenarios with machine-checked closed-loop invariants.

Each scenario wires a workload (sim/traces.py) through a SimFleet
(sim/fleet.py) on a virtual clock and asserts *control-plane properties* —
not point metrics but the loop behaviors ROADMAP item 3 needs proven:

- ``diurnal-autoscale``   planner tracks a diurnal load without oscillating
- ``bursty-breaker-chaos``  per-worker breakers trip on injected flaps,
                            steer traffic around them with bounded goodput
                            loss, and re-admit the worker after recovery
- ``prefix-heavy-radix``  KV routing keeps radix reuse high and queue
                            fairness intact under a hot shared-prefix group
- ``multi-pool-balance``  grid pool selection (global_router) splits SLA
                            classes onto the right pools and keeps the
                            interactive pool isolated from batch load
- ``multi-region-follow-sun``  phase-shifted regional diurnals keep the
                            combined fleet busy while each region holds SLA

Scenarios scale with ``workers`` and ``duration_s`` so the same invariants
run as a tier-1 smoke (small fleet, ~4 simulated minutes, seconds of wall
time) and as the full CLI gate (hundreds of workers, 10+ simulated
minutes). Every knob derives from (seed, workers, duration_s) only: same
inputs => byte-identical deterministic report section (sim/report.py).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..global_router.pool_selection import PrefillPoolSelectionStrategy
from ..runtime.resilience import OPEN
from . import clock as simclock
from . import traces
from .fleet import FleetConfig, PoolConfig, SimFleet, worker_fault_point
from .report import Invariant, scenario_report

# per-worker mocker speed used by every scenario: slow enough that tens to
# hundreds of workers are *needed* at single-digit req/s rates (keeping the
# python step count — the wall cost — low), fast enough that a pool keeps
# its SLA with headroom. One worker sustains ~0.5 req/s of the default
# isl=256/osl=12 shape (measured; capacity_req_s below is the planner's
# profile of the same number).
_SPEED = dict(
    prefill_base_s=0.8,
    prefill_per_token_s=6.5e-3,
    decode_base_s=0.4,
    decode_per_kv_block_s=1e-5,
)
_CAPACITY_REQ_S = 0.3


def _invariant(name: str, ok: bool, detail: str) -> Invariant:
    return Invariant(name, bool(ok), detail)


def _slo_attainment(pool, sla_class: str, kind: str = "ttft") -> float:
    """Cumulative attainment from the pool's production ``SloAccountant``
    (sim/fleet.py feeds it per completed request on the virtual clock)."""
    att = pool.slo.attainment("sim", sla_class, window="total", kind=kind)
    return round(att, 4) if att is not None else 0.0


def _trace_ttft_attainment(pool) -> float:
    """The scenario-local math the accountant replaces — kept only as the
    agreement counterfactual for the mixed-SLA check."""
    done = [r for r in pool.records if r.ok]
    return round(
        sum(1 for r in done if r.ttft_s <= r.ttft_target_s)
        / max(len(done), 1), 4,
    )


# ---------------------------------------------------------------------------
# diurnal-autoscale
# ---------------------------------------------------------------------------


async def _diurnal_autoscale(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    periods = 2
    amplitude = 0.8
    peak_rate = 0.55 * workers * _CAPACITY_REQ_S
    mean_rate = peak_rate / (1 + amplitude)
    trace = traces.diurnal(
        duration_s=duration_s, mean_rate=mean_rate, amplitude=amplitude,
        period_s=duration_s / periods, isl=256, osl=12, seed=seed,
        # targets sized to the slow worker model: ~1.5s prefill + queueing
        # + up to 5s boot when a request lands on a just-spawned worker
        ttft_target_s=18.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[PoolConfig(
            name="decode", initial_workers=max(2, workers // 8),
            min_workers=1, max_workers=workers,
            autoscale=True, adjustment_interval_s=10.0,
            capacity_req_s=_CAPACITY_REQ_S, startup_time_s=5.0,
            scale_down_headroom=0.7,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import direction_flips, pool_report

    rep = pool_report(pool)
    replicas = [n for _, n in pool.replica_timeline]
    flips = direction_flips(replicas)
    peak = max(replicas) if replicas else 0
    final = replicas[-1] if replicas else 0
    invs = [
        _invariant(
            "scaled_up", peak >= max(3, int(0.35 * workers)),
            f"peak replicas {peak} (cap {workers})",
        ),
        _invariant(
            "scaled_back_down", final <= max(2, int(0.7 * peak)),
            f"final {final} vs peak {peak}",
        ),
        _invariant(
            "no_oscillation", flips <= 3 * periods,
            f"{flips} resize-direction flips over {periods} periods "
            f"(bound {3 * periods})",
        ),
        _invariant(
            "all_completed", rep["failed"] == 0,
            f'{rep["completed"]}/{rep["requests"]} completed',
        ),
        # re-derived from the production SloAccountant (runtime/slo.py) on
        # the virtual clock, not scenario-local percentile math
        _invariant(
            "ttft_sla_held", _slo_attainment(pool, "standard") >= 0.75,
            f'accountant ttft attainment '
            f'{_slo_attainment(pool, "standard")} (>= 0.75)',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# bursty-breaker-chaos
# ---------------------------------------------------------------------------


async def _bursty_breaker_chaos(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    flap_wid = 1  # first-spawned worker flaps
    flap_until = 0.6 * duration_s
    trace = traces.bursty(
        duration_s=duration_s,
        base_rate=0.15 * workers * _CAPACITY_REQ_S,
        burst_rate=0.9 * workers * _CAPACITY_REQ_S,
        burst_len_s=duration_s / 8, cycle_s=duration_s / 4,
        isl=256, osl=12, seed=seed, ttft_target_s=15.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5, max_attempts=4,
        # the flapping worker drops 95% of its dispatches on a seeded
        # schedule; a thin event-plane drop keeps the router view noisy too
        faults=(
            f"{worker_fault_point(flap_wid)}:drop@p=0.95@seed={seed + 17};"
            f"event_plane.publish:drop@p=0.02@seed={seed + 23}"
        ),
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=workers, max_workers=workers,
            breaker_threshold=3, breaker_window_s=60.0,
            breaker_reset_s=duration_s / 6,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()

    async def _recover() -> None:
        await clock.sleep(flap_until)
        fleet.disarm_fault(worker_fault_point(flap_wid))

    fleet.spawn_task(_recover())
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import pool_report

    rep = pool_report(pool)
    opens = [t for t, wid, st in pool.breaker_events
             if wid == flap_wid and st == OPEN]
    first_open = opens[0] if opens else float("inf")
    done = [r for r in pool.records if r.ok]
    during = [r for r in done if first_open <= r.t_arrive <= flap_until]
    on_flapped = sum(1 for r in during if r.worker == flap_wid)
    share_during = on_flapped / max(len(during), 1)
    fair = 1.0 / workers
    after = [r for r in done
             if r.t_arrive > flap_until + pool.cfg.breaker_reset_s]
    recovered = sum(1 for r in after if r.worker == flap_wid)
    goodput = rep["completed"] / max(rep["requests"], 1)
    invs = [
        _invariant(
            "breaker_tripped", bool(opens),
            f"worker {flap_wid} breaker opened at t={opens[:3]}",
        ),
        _invariant(
            "goodput_held", goodput >= 0.99,
            f"goodput {goodput:.4f} with {rep['retries']} retries "
            "(retry-then-migrate absorbs the flap)",
        ),
        _invariant(
            "steered_around", share_during <= 0.5 * fair,
            f"flapping worker served {share_during:.4f} of traffic while "
            f"tripped (fair share {fair:.4f})",
        ),
        _invariant(
            "recovered_after_flap", recovered >= 1,
            f"worker {flap_wid} served {recovered} requests after recovery",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# prefix-heavy-radix
# ---------------------------------------------------------------------------


async def _prefix_heavy_radix(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    num_groups = max(4, workers)
    # run the fleet at ~60% utilization: saturated workers would make the
    # scheduler's load term rightly override radix affinity, which is the
    # steady-state this scenario is NOT about
    trace = traces.prefix_heavy(
        duration_s=duration_s, rate=0.35 * workers * _CAPACITY_REQ_S,
        isl=256, osl=12, num_groups=num_groups, hot_group_share=0.4,
        seed=seed, ttft_target_s=10.0, itl_target_s=3.0,
    )
    cfg = FleetConfig(
        seed=seed, prefix_share=0.75,
        pools=[PoolConfig(
            name="decode", initial_workers=workers,
            min_workers=workers, max_workers=workers,
            **_SPEED,
        )],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace)
    finally:
        await fleet.stop()

    pool = fleet.default_pool
    from .report import pool_report

    rep = pool_report(pool)
    done = [r for r in pool.records if r.ok]
    by_group: Dict[int, List] = {}
    for r in done:
        by_group.setdefault(r.group, []).append(r)
    # radix routing's per-request effect: the engine confirmed (via
    # cached_tokens on the first output) that the chosen worker already
    # held most of the shared prefix. Group members may legitimately span
    # several workers — the scheduler *replicates* a hot prefix when its
    # holders are loaded — so the property is reuse-on-arrival, not
    # single-worker affinity.
    shared_len = 0.75 * 256
    prefix_routed = sum(
        1 for r in done if r.cached_tokens >= 0.75 * shared_len
    ) / max(len(done), 1)
    # fairness: cold groups must not starve behind the hot group
    cold_attain = [
        sum(1 for r in rs if r.ttft_s <= r.ttft_target_s) / len(rs)
        for g, rs in sorted(by_group.items()) if g != 0 and len(rs) >= 10
    ]
    worst_cold = min(cold_attain) if cold_attain else 1.0
    used_workers = {r.worker for r in done}
    invs = [
        _invariant(
            "radix_reuse", rep["cache_hit_ratio"] >= 0.4,
            f'cache hit ratio {rep["cache_hit_ratio"]} '
            "(0.75 of each group prompt is shared)",
        ),
        _invariant(
            "prefix_routed", prefix_routed >= 0.7,
            f"{prefix_routed:.3f} of requests landed on a worker already "
            "holding >=75% of their shared prefix",
        ),
        _invariant(
            "queue_fairness", worst_cold >= 0.6,
            f"worst cold-group TTFT attainment {worst_cold:.3f} "
            "(hot group must not starve the rest)",
        ),
        _invariant(
            "fleet_spread", len(used_workers) >= max(2, int(0.75 * workers)),
            f"{len(used_workers)}/{workers} workers served traffic",
        ),
        _invariant(
            "all_completed", rep["failed"] == 0,
            f'{rep["completed"]}/{rep["requests"]} completed',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# multi-pool-balance
# ---------------------------------------------------------------------------


async def _multi_pool_balance(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    w_inter = max(2, workers // 2)
    w_batch = max(2, workers - w_inter)
    classes = [
        {"name": "interactive", "weight": 0.65, "isl": 128, "osl": 8,
         "ttft_target_s": 8.0, "itl_target_s": 3.0},
        {"name": "batch", "weight": 0.35, "isl": 1024, "osl": 24,
         "ttft_target_s": 60.0, "itl_target_s": 3.0},
    ]
    # interactive pool is sized for short prompts; batch pool absorbs the
    # heavy ISL class (its per-request cost is ~8x the interactive one)
    rate = 0.55 * w_inter * _CAPACITY_REQ_S / classes[0]["weight"] * 0.5
    trace = traces.sla_classes(
        duration_s=duration_s, rate=rate, classes=classes, seed=seed,
    )
    # the real global_router grid: (ISL, TTFT target) -> pool index
    strategy = PrefillPoolSelectionStrategy(
        ttft_min=0.0, ttft_max=60.0, ttft_resolution=2,
        isl_min=0, isl_max=2048, isl_resolution=2,
        prefill_pool_mapping=[[0, 0], [1, 1]],
    )
    pool_names = ["interactive", "batch"]
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[
            PoolConfig(
                name="interactive", namespace="sim-inter",
                initial_workers=w_inter, min_workers=w_inter,
                max_workers=w_inter, **_SPEED,
            ),
            PoolConfig(
                name="batch", namespace="sim-batch",
                initial_workers=w_batch, min_workers=w_batch,
                max_workers=w_batch, **_SPEED,
            ),
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()

    def pool_for(sreq: traces.SimRequest) -> str:
        idx = strategy.select_pool(sreq.item.isl, sreq.ttft_target_s)
        return pool_names[idx]

    try:
        await fleet.run_trace(trace, pool_for=pool_for)
    finally:
        await fleet.stop()

    from .report import pool_report

    inter, batch = fleet.pools["interactive"], fleet.pools["batch"]
    rep_i, rep_b = pool_report(inter), pool_report(batch)
    misrouted = (
        sum(1 for r in inter.records if r.isl >= 1024)
        + sum(1 for r in batch.records if r.isl < 1024)
    )
    # in-pool balance: no worker hoards traffic
    def max_share(rep: dict) -> float:
        counts = list(rep["per_worker_requests"].values())
        return max(counts) / max(sum(counts), 1) if counts else 0.0

    fair_i = 1.0 / w_inter
    invs = [
        _invariant(
            "selection_correct", misrouted == 0,
            f"{misrouted} requests landed in the wrong pool "
            "(grid: isl<1024 -> interactive)",
        ),
        _invariant(
            "all_completed", rep_i["failed"] == 0 and rep_b["failed"] == 0,
            f'interactive {rep_i["completed"]}/{rep_i["requests"]}, '
            f'batch {rep_b["completed"]}/{rep_b["requests"]}',
        ),
        _invariant(
            "interactive_isolated", rep_i["ttft_attainment"] >= 0.9,
            f'interactive TTFT attainment {rep_i["ttft_attainment"]} '
            "despite batch-class load on the fleet",
        ),
        _invariant(
            "in_pool_balance", max_share(rep_i) <= 3.0 * fair_i,
            f"hottest interactive worker share {max_share(rep_i):.3f} "
            f"(fair {fair_i:.3f})",
        ),
        # mixed-SLA-classes accounting: the production SloAccountant's
        # per-class ledger must (a) hold the interactive promise and (b)
        # agree exactly with the trace-derived attainment — proving the
        # accountant code path on deterministic virtual time
        _invariant(
            "mixed_sla_classes_accounted",
            _slo_attainment(inter, "interactive") >= 0.9
            and _slo_attainment(inter, "interactive")
            == _trace_ttft_attainment(inter)
            and _slo_attainment(batch, "batch")
            == _trace_ttft_attainment(batch),
            f'accountant interactive {_slo_attainment(inter, "interactive")} '
            f'(trace {_trace_ttft_attainment(inter)}), '
            f'batch {_slo_attainment(batch, "batch")} '
            f'(trace {_trace_ttft_attainment(batch)})',
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# multi-region-follow-sun
# ---------------------------------------------------------------------------


async def _multi_region_follow_sun(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    regions = 2
    per_region = max(2, workers // regions)
    amplitude = 0.8
    peak_rate = 0.5 * per_region * _CAPACITY_REQ_S
    region_traces = traces.multi_region(
        regions=regions, duration_s=duration_s,
        mean_rate=peak_rate / (1 + amplitude), amplitude=amplitude,
        isl=256, osl=12, seed=seed, ttft_target_s=12.0, itl_target_s=3.0,
    )
    trace = traces.merge(*region_traces.values())
    cfg = FleetConfig(
        seed=seed, prefix_share=0.5,
        pools=[
            PoolConfig(
                name=f"r{i}", namespace=f"sim-r{i}",
                initial_workers=per_region, min_workers=per_region,
                max_workers=per_region, **_SPEED,
            )
            for i in range(regions)
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    try:
        await fleet.run_trace(trace, pool_for=lambda sr: sr.region)
    finally:
        await fleet.stop()

    from .report import pool_report

    reps = {name: pool_report(p) for name, p in fleet.pools.items()}
    # per-region attainment from each pool's production SloAccountant
    # (was scenario-local percentile math before the slo plane landed)
    attains = {name: _slo_attainment(p, "standard")
               for name, p in fleet.pools.items()}
    counts = {name: r["requests"] for name, r in reps.items()}
    total = sum(counts.values())
    shares = {n: c / max(total, 1) for n, c in counts.items()}
    invs = [
        _invariant(
            "regions_balanced",
            max(shares.values()) - min(shares.values()) <= 0.15,
            f"request shares {shares} (phase-shifted peaks, near-even total)",
        ),
        _invariant(
            "all_regions_hold_sla", min(attains.values()) >= 0.75,
            f"per-region TTFT attainment {attains}",
        ),
        _invariant(
            "all_completed",
            all(r["failed"] == 0 for r in reps.values()),
            f"completed per region {dict((n, r['completed']) for n, r in reps.items())}",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# disagg-streamed-prefill
# ---------------------------------------------------------------------------


async def _disagg_streamed_prefill(
    clock: simclock.VirtualClock, seed: int, workers: int, duration_s: float
) -> Dict:
    """Disaggregated prefill/decode with the REAL PrefillRouter in the loop
    (ROADMAP item 3 remainder): every arrival is planned by
    ``PrefillRouter.plan`` — transfer-cost-aware candidate scoring over the
    prefill pool's real KvRouter, short-prompt/radix/load deflection — then
    the prefill leg runs on a mocker prefill pool and the decode leg on the
    decode pool, with the wire modeled per request by the deterministic
    ``ops.costs.streamed_transfer_model`` at the scenario's per-worker wire
    classes. Invariants gate the PR 10 acceptance criteria: streamed TTFT
    <= the blocking counterfactual, deflection active under the load mix,
    cost-aware steering toward fast-wire workers, and disagg TTFT within
    1.15x of an equal-capacity colocated twin fleet on the same trace."""
    import asyncio

    from ..llm.model_card import ModelDeploymentCard
    from ..llm.prefill_router import DisaggConfig, PrefillRouter
    from ..llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from ..ops.costs import streamed_transfer_model
    from ..profiler.loadgen import prefix_prompt
    from ..runtime.bandwidth import WireBandwidthEstimator
    from ..runtime.engine import Context
    from .traces import SimRequest, TraceItem

    block_size = 16
    prefill_chunk = 512
    kv_bytes_per_block = 2 << 20            # a ~70B-class bf16 block
    speed = dict(_SPEED, prefill_base_s=0.2)
    # wire classes per prefill worker: even ids sit a native hop away, odd
    # ids only reach the decode pool over a congested inline path — the
    # skew the cost-aware router must price
    wire_priors = {"native": 2.0e9, "inline": 1.0e8}

    p_workers = max(2, workers // 2)
    d_workers = max(2, workers - p_workers)
    long_isl, short_isl, osl = 2048, 48, 12
    long_w = 0.65
    prefill_cost_long = speed["prefill_base_s"] + speed["prefill_per_token_s"] * long_isl
    rate = 0.35 * p_workers / (long_w * prefill_cost_long)
    classes = [
        {"weight": 1 - long_w, "isl": short_isl, "osl": osl,
         "ttft_target_s": 10.0, "itl_target_s": 3.0},
        {"weight": long_w, "isl": long_isl, "osl": osl,
         "ttft_target_s": 30.0, "itl_target_s": 3.0},
    ]
    trace = traces.sla_classes(
        duration_s=duration_s, rate=rate, classes=classes, seed=seed,
    )

    dcfg = DisaggConfig(
        streamed=True, deflect=True,
        deflect_max_tokens=64, deflect_overlap_frac=0.5, deflect_margin=2.0,
        prefill_block_time_s=speed["prefill_per_token_s"] * block_size,
        kv_bytes_per_block=kv_bytes_per_block,
    )

    # ---- phase 1: disagg fleet (decode pool + prefill pool) ----------------
    cfg = FleetConfig(
        seed=seed, prefix_share=0.0,
        pools=[
            PoolConfig(
                name="decode", namespace="sim-dec",
                initial_workers=d_workers, min_workers=d_workers,
                max_workers=d_workers, block_size=block_size, **speed,
            ),
            PoolConfig(
                name="prefill", namespace="sim-pre",
                initial_workers=p_workers, min_workers=p_workers,
                max_workers=p_workers, block_size=block_size, **speed,
            ),
        ],
    )
    fleet = SimFleet(cfg, clock)
    await fleet.start()
    decode_pool = fleet.pools["decode"]
    prefill_pool = fleet.pools["prefill"]

    p_wids = sorted(prefill_pool.workers)
    wires = {
        wid: ("native" if i < (len(p_wids) + 1) // 2 else "inline")
        for i, wid in enumerate(p_wids)
    }

    class _Inst:
        def __init__(self, wid: int):
            self.metadata = {
                "data_parallel_size": 1,
                "transfer_address": f"sim://prefill/{wid}",
                "kv_wire": wires[wid],
            }

    class _StubClient:
        """The real Client surface PrefillRouter.plan reads."""

        @property
        def instances(self):
            return {wid: _Inst(wid) for wid in sorted(prefill_pool.workers)}

    prefill_card = ModelDeploymentCard(
        name="sim", component="prefill", kv_block_size=block_size,
    )
    router = PrefillRouter(runtime=None, card=prefill_card, disagg=dcfg)
    router.client = _StubClient()
    router.kv_router = prefill_pool.router        # the REAL prefill KvRouter
    router.bandwidth = WireBandwidthEstimator(priors=wire_priors)

    streamed_ttfts: List[float] = []
    blocking_ttfts: List[float] = []
    deflect_reasons: Dict[str, int] = {}
    disagg_wires: List[str] = []
    failures = [0]

    async def _prefill_leg(wid: int, rid: str, tokens: List[int]) -> float:
        w = prefill_pool.workers.get(wid)
        if w is None:  # retired between plan and dispatch: any worker
            w = next(iter(prefill_pool.workers.values()))
        req = PreprocessedRequest(
            request_id=rid, model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=1, min_tokens=1, ignore_eos=True),
            sampling=SamplingOptions(temperature=0.0),
        )
        t0 = clock.time()
        async for out in w.engine.generate(req, Context(rid)):
            if out.finish_reason is not None:
                break
        return clock.time() - t0

    async def _one(idx: int, sreq: SimRequest) -> None:
        item = sreq.item
        t_arr = clock.time()
        tokens = prefix_prompt(item, idx, fleet.cfg.prefix_share)
        preq = PreprocessedRequest(
            request_id=f"sim-disagg-{idx}", model="sim", token_ids=tokens,
            stop=StopConditions(max_tokens=item.osl),
            sampling=SamplingOptions(temperature=0.0),
        )
        overlap = decode_pool.router.score_tokens(
            tokens, decode_pool._candidates()
        ).overlap_blocks if decode_pool.workers else 0
        plan = router.plan(preq, decode_overlap_blocks=overlap)
        if plan is None or plan.deflected:
            reason = plan.deflect_reason if plan is not None else "no_candidates"
            deflect_reasons[reason] = deflect_reasons.get(reason, 0) + 1
            rec = await decode_pool.submit(idx, sreq)
            if not rec.ok:
                failures[0] += 1
                return
            streamed_ttfts.append(rec.ttft_s)
            blocking_ttfts.append(rec.ttft_s)  # no wire either way
            return
        disagg_wires.append(plan.wire)
        prefill_s = await _prefill_leg(
            plan.worker_id, f"{preq.request_id}.p", tokens
        )
        chunks = max(-(-item.isl // prefill_chunk), 1)
        model = streamed_transfer_model(
            item.isl,
            block_size=block_size,
            prefill_chunk=prefill_chunk,
            kv_bytes_per_block=kv_bytes_per_block,
            bandwidth_bytes_s=router.bandwidth.bandwidth(plan.wire),
            prefill_chunk_s=prefill_s / chunks,
            window_blocks=8,
        )
        streamed_extra = max(model["streamed_ttft_s"] - model["prefill_s"], 0.0)
        blocking_extra = max(model["blocking_ttft_s"] - model["prefill_s"], 0.0)
        router.bandwidth.observe(plan.wire, model["bytes"], model["transfer_s"])
        if streamed_extra > 0:
            await clock.sleep(streamed_extra)  # the un-hidden wire tail
        # decode leg: the transferred prefix is resident; only the final
        # partial block's tokens are recomputed on the decode worker
        tail = item.isl % block_size or block_size
        tail_req = SimRequest(
            TraceItem(item.t, tail, item.osl, item.group),
            ttft_target_s=sreq.ttft_target_s, itl_target_s=sreq.itl_target_s,
            region=sreq.region,
        )
        t_submit = clock.time()
        rec = await decode_pool.submit(idx, tail_req, tokens=tokens[-tail:])
        if not rec.ok:
            failures[0] += 1
            return
        ttft = (t_submit - t_arr) + rec.ttft_s
        streamed_ttfts.append(ttft)
        blocking_ttfts.append(ttft + (blocking_extra - streamed_extra))

    try:
        tasks: List[asyncio.Task] = []
        t_prev = 0.0
        for idx, sreq in enumerate(trace):
            dt = sreq.t - t_prev
            t_prev = sreq.t
            if dt > 0:
                await clock.sleep(dt)
            tasks.append(asyncio.create_task(_one(idx, sreq)))
        if tasks:
            await asyncio.gather(*tasks)
    finally:
        await fleet.stop()

    # ---- phase 2: colocated twin (equal capacity, same trace) --------------
    colo_cfg = FleetConfig(
        seed=seed, prefix_share=0.0,
        pools=[PoolConfig(
            name="colocated", namespace="sim-colo",
            initial_workers=d_workers + p_workers,
            min_workers=d_workers + p_workers,
            max_workers=d_workers + p_workers,
            block_size=block_size, **speed,
        )],
    )
    colo = SimFleet(colo_cfg, clock)
    await colo.start()
    try:
        await colo.run_trace(trace)
    finally:
        await colo.stop()

    from ..profiler.loadgen import pct

    colo_ttfts = sorted(
        r.ttft_s for r in colo.pools["colocated"].records if r.ok
    )
    s_sorted = sorted(streamed_ttfts)
    b_sorted = sorted(blocking_ttfts)
    p50_s, p50_b = pct(s_sorted, 0.5), pct(b_sorted, 0.5)
    mean_s = sum(s_sorted) / max(len(s_sorted), 1)
    mean_b = sum(b_sorted) / max(len(b_sorted), 1)
    p50_colo = pct(colo_ttfts, 0.5)
    n_total = len(trace)
    n_deflected = sum(deflect_reasons.values())
    share = n_deflected / max(n_total, 1)
    fast_share = (
        sum(1 for w in disagg_wires if w == "native") / len(disagg_wires)
        if disagg_wires else 0.0
    )
    colo_failed = sum(1 for r in colo.pools["colocated"].records if not r.ok)
    invs = [
        _invariant(
            "streamed_le_blocking",
            p50_s <= p50_b and (not disagg_wires or mean_s < mean_b),
            f"streamed TTFT p50 {p50_s:.3f}s mean {mean_s:.3f}s vs blocking "
            f"counterfactual p50 {p50_b:.3f}s mean {mean_b:.3f}s "
            f"({len(disagg_wires)} disagg requests)",
        ),
        _invariant(
            "deflection_active",
            0.15 <= share <= 0.85 and deflect_reasons.get("short_prompt", 0) > 0,
            f"deflected {n_deflected}/{n_total} ({share:.3f}) by reason "
            f"{dict(sorted(deflect_reasons.items()))}",
        ),
        _invariant(
            "wire_cost_steering", fast_share >= 0.55,
            f"{fast_share:.3f} of disagg prefills landed on native-wire "
            "workers (half the pool; cost-blind routing would give ~0.5)",
        ),
        _invariant(
            "near_colocated_ttft", p50_s <= 1.15 * p50_colo,
            f"disagg TTFT p50 {p50_s:.3f}s vs colocated {p50_colo:.3f}s "
            f"(bound 1.15x = {1.15 * p50_colo:.3f}s)",
        ),
        _invariant(
            "all_completed", failures[0] == 0 and colo_failed == 0,
            f"disagg failures {failures[0]}, colocated failures {colo_failed}",
        ),
    ]
    return {"fleet": fleet, "invariants": invs, "requests": len(trace)}


# ---------------------------------------------------------------------------
# registry + runner
# ---------------------------------------------------------------------------

SCENARIOS: Dict[str, Callable] = {
    "diurnal-autoscale": _diurnal_autoscale,
    "bursty-breaker-chaos": _bursty_breaker_chaos,
    "prefix-heavy-radix": _prefix_heavy_radix,
    "multi-pool-balance": _multi_pool_balance,
    "multi-region-follow-sun": _multi_region_follow_sun,
    "disagg-streamed-prefill": _disagg_streamed_prefill,
}

# aliases accepted by the CLI (`python -m dynamo_tpu.sim diurnal`)
ALIASES = {
    "diurnal": "diurnal-autoscale",
    "bursty": "bursty-breaker-chaos",
    "prefix": "prefix-heavy-radix",
    "multipool": "multi-pool-balance",
    "regions": "multi-region-follow-sun",
    "disagg": "disagg-streamed-prefill",
}


def resolve(name: str) -> str:
    full = ALIASES.get(name, name)
    if full not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)} "
            f"(aliases {sorted(ALIASES)})"
        )
    return full


def run_scenario(
    name: str,
    seed: int = 0,
    workers: int = 8,
    duration_s: Optional[float] = None,
) -> dict:
    """Run one scenario to completion on a fresh virtual-time loop and
    return its report (sim/report.py schema). Blocking; call from sync
    code (CLI, bench.py, tests)."""
    full = resolve(name)
    duration = float(duration_s) if duration_s is not None else 240.0
    t0 = time.perf_counter()

    async def main(clock: simclock.VirtualClock):
        return await SCENARIOS[full](clock, seed, workers, duration), clock

    out, clock = simclock.run(main)
    return scenario_report(
        name=full, seed=seed, fleet=out["fleet"],
        invariants=out["invariants"], sim_duration_s=duration,
        wall_elapsed_s=time.perf_counter() - t0,
        extra_sim={"workers": workers, "trace_requests": out["requests"]},
        sim_advanced_s=clock.advanced,
    )


def run_suite(
    names: Optional[List[str]] = None,
    seed: int = 0,
    workers: int = 8,
    duration_s: Optional[float] = None,
) -> List[dict]:
    """The perf-gate suite: the four gate scenarios (plus any extras asked
    for) at the given scale."""
    gate = names or [
        "diurnal-autoscale", "bursty-breaker-chaos",
        "prefix-heavy-radix", "multi-pool-balance",
        "disagg-streamed-prefill",
    ]
    return [
        run_scenario(n, seed=seed, workers=workers, duration_s=duration_s)
        for n in gate
    ]
