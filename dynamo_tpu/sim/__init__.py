"""dynamo_tpu.sim: deterministic virtual-time fleet simulation.

Hundreds of mocker workers behind the *real* control plane (kv_router,
planner, pool selection, breakers, fault injection) in one process, driven
on a virtual clock so minutes-long traces replay in CI seconds with
same-seed -> bit-identical reports. See docs/operations.md
"Fleet simulation & perf gate".

The injectable ``Clock`` base lives in ``runtime/clock.py`` (so core
modules like the mocker and loadgen never import from this package);
``sim.clock`` adds the virtual driver and re-exports the base. Heavier
submodules are imported lazily to keep ``import dynamo_tpu.sim`` cheap.
"""

from __future__ import annotations

import importlib

from .clock import WALL, Clock, VirtualClock, VirtualTimeStall, run  # noqa: F401

_LAZY = ("traces", "fleet", "scenarios", "report")


def __getattr__(name: str):
    if name in _LAZY:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
