"""CLI: ``python -m dynamo_tpu.sim <scenario> [--workers N] [--seed S] ...``

Runs one named scenario (or ``suite`` for the perf-gate four) on the
virtual clock and prints its report JSON. Exit code 1 if any invariant
failed — usable directly as a CI gate.

Examples::

    python -m dynamo_tpu.sim diurnal --workers 100
    python -m dynamo_tpu.sim bursty-breaker-chaos --seed 7 --duration 600
    python -m dynamo_tpu.sim suite --workers 24 --out report.json
    python -m dynamo_tpu.sim list
"""

from __future__ import annotations

import argparse
import json
import sys

from .scenarios import ALIASES, SCENARIOS, run_scenario, run_suite


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.sim",
        description="deterministic virtual-time fleet simulator",
    )
    ap.add_argument(
        "scenario",
        help="scenario name or alias (see 'list'), or 'suite' for the "
             "perf-gate four",
    )
    ap.add_argument("--workers", type=int, default=100,
                    help="fleet size / autoscale cap (default 100)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=720.0,
                    help="simulated seconds to replay (default 720 = 12 min)")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON to this path")
    ap.add_argument("--wall", action="store_true",
                    help="include the non-deterministic wall section in "
                         "stdout (always present in --out)")
    args = ap.parse_args(argv)

    if args.scenario == "list":
        for name in sorted(SCENARIOS):
            short = [a for a, full in ALIASES.items() if full == name]
            print(f"{name}" + (f"  (alias: {short[0]})" if short else ""))
        return 0

    if args.scenario == "suite":
        reports = run_suite(seed=args.seed, workers=args.workers,
                            duration_s=args.duration)
    else:
        reports = [run_scenario(args.scenario, seed=args.seed,
                                workers=args.workers,
                                duration_s=args.duration)]

    if args.out:
        # always a list, so the file's shape doesn't depend on how many
        # scenarios ran (single run vs suite)
        with open(args.out, "w") as f:
            json.dump(reports, f, indent=2, sort_keys=True)
    ok = True
    for rep in reports:
        shown = dict(rep) if args.wall else {"sim": rep["sim"]}
        print(json.dumps(shown, indent=2, sort_keys=True))
        sim = rep["sim"]
        ok = ok and sim["passed"]
        status = "PASS" if sim["passed"] else "FAIL"
        bad = [iv["name"] for iv in sim["invariants"] if not iv["ok"]]
        print(
            f'# {sim["scenario"]}: {status} '
            f'({len(sim["invariants"])} invariants'
            + (f", failing: {bad}" if bad else "")
            + f'; {rep["wall"]["elapsed_s"]}s wall for '
            f'{sim["sim_advanced_s"]}s simulated)',
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
