"""Seeded workload library for the fleet simulator.

Builds on the ``profiler/loadgen.py`` TraceItem model (arrival t, isl, osl,
prefix group) and its arrival processes; adds the shapes the scenario suite
needs: heavy-tail ISL/OSL, hot-group prefix skew, SLA classes, and
phase-shifted multi-region diurnals. Every builder is a pure function of its
seed — same seed, same trace, byte for byte.

Reference analogs: benchmarks/sin_load_generator (diurnal),
benchmarks/burstgpt_loadgen (bursty replay), prefix_data_generator
(controlled shared-prefix share).
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional

from ..profiler.loadgen import TraceItem, bursty_trace, sinusoidal_trace


@dataclasses.dataclass
class SimRequest:
    """One sim arrival: a TraceItem plus routing metadata the control plane
    reads (SLA targets feed pool selection; ``region`` tags multi-region
    traffic for the balance invariants)."""

    item: TraceItem
    ttft_target_s: float = 0.5
    itl_target_s: float = 0.05
    region: str = "r0"
    # SLA class name (runtime/slo.py): keys the SloAccountant series the
    # fleet feeds, so scenario invariants read per-class attainment from
    # the production accountant instead of scenario-local math
    sla_class: str = "standard"

    @property
    def t(self) -> float:
        return self.item.t


def _wrap(
    items: List[TraceItem],
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
    region: str = "r0",
) -> List[SimRequest]:
    return [
        SimRequest(it, ttft_target_s=ttft_target_s,
                   itl_target_s=itl_target_s, region=region)
        for it in items
    ]


def diurnal(
    duration_s: float,
    mean_rate: float,
    amplitude: float = 0.8,
    period_s: Optional[float] = None,
    isl: int = 256,
    osl: int = 24,
    num_groups: int = 16,
    seed: int = 0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
) -> List[SimRequest]:
    """Diurnal sinusoid: two full periods by default so the autoscale
    invariants see a ramp-up, a peak, a ramp-down and a second cycle."""
    period = period_s if period_s is not None else duration_s / 2.0
    return _wrap(sinusoidal_trace(
        duration_s=duration_s, mean_rate=mean_rate, amplitude=amplitude,
        period_s=period, isl=isl, osl=osl, num_groups=num_groups, seed=seed,
    ), ttft_target_s=ttft_target_s, itl_target_s=itl_target_s)


def bursty(
    duration_s: float,
    base_rate: float,
    burst_rate: float,
    burst_len_s: float,
    cycle_s: float,
    isl: int = 256,
    osl: int = 24,
    num_groups: int = 16,
    seed: int = 0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
) -> List[SimRequest]:
    """BurstGPT-style on/off bursts."""
    return _wrap(bursty_trace(
        duration_s=duration_s, base_rate=base_rate, burst_rate=burst_rate,
        burst_len_s=burst_len_s, cycle_s=cycle_s, isl=isl, osl=osl,
        num_groups=num_groups, seed=seed,
    ), ttft_target_s=ttft_target_s, itl_target_s=itl_target_s)


def heavy_tail(
    duration_s: float,
    rate: float,
    isl_median: int = 256,
    isl_sigma: float = 0.8,
    osl_median: int = 24,
    osl_sigma: float = 0.6,
    max_isl: int = 4096,
    max_osl: int = 256,
    num_groups: int = 16,
    seed: int = 0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
) -> List[SimRequest]:
    """Poisson arrivals with log-normal ISL/OSL (the production shape: most
    prompts short, a fat tail of very long ones)."""
    rng = random.Random(seed)
    out: List[TraceItem] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        isl = min(max_isl, max(16, int(rng.lognormvariate(
            math.log(isl_median), isl_sigma))))
        osl = min(max_osl, max(4, int(rng.lognormvariate(
            math.log(osl_median), osl_sigma))))
        out.append(TraceItem(t, isl, osl, rng.randrange(num_groups)))
    return _wrap(out, ttft_target_s=ttft_target_s, itl_target_s=itl_target_s)


def prefix_heavy(
    duration_s: float,
    rate: float,
    isl: int = 512,
    osl: int = 16,
    num_groups: int = 8,
    hot_group_share: float = 0.5,
    seed: int = 0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
) -> List[SimRequest]:
    """Shared-prefix-ratio workload with a hot group: ``hot_group_share`` of
    requests hit group 0 (the agent-loop / system-prompt pattern radix
    routing exists for), the rest spread uniformly over the other groups."""
    rng = random.Random(seed)
    out: List[TraceItem] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        if rng.random() < hot_group_share:
            g = 0
        else:
            g = 1 + rng.randrange(max(num_groups - 1, 1))
        out.append(TraceItem(t, isl, osl, g))
    return _wrap(out, ttft_target_s=ttft_target_s, itl_target_s=itl_target_s)


def sla_classes(
    duration_s: float,
    rate: float,
    classes: Optional[List[dict]] = None,
    num_groups: int = 16,
    seed: int = 0,
) -> List[SimRequest]:
    """Mixed SLA-class traffic for pool selection: each arrival draws a
    class (weight, isl, osl, ttft/itl targets). Defaults model 'interactive'
    (short prompt, tight TTFT) vs 'batch' (long prompt, loose TTFT) —
    the two-pool grid in the multi-pool scenario keys off exactly this."""
    cls = classes or [
        {"name": "interactive", "weight": 0.6, "isl": 128, "osl": 16,
         "ttft_target_s": 0.3, "itl_target_s": 0.05},
        {"name": "batch", "weight": 0.4, "isl": 1024, "osl": 48,
         "ttft_target_s": 2.0, "itl_target_s": 0.2},
    ]
    weights = [c["weight"] for c in cls]
    rng = random.Random(seed)
    out: List[SimRequest] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        c = rng.choices(cls, weights=weights)[0]
        out.append(SimRequest(
            TraceItem(t, int(c["isl"]), int(c["osl"]),
                      rng.randrange(num_groups)),
            ttft_target_s=float(c["ttft_target_s"]),
            itl_target_s=float(c["itl_target_s"]),
            sla_class=str(c.get("name", "standard")),
        ))
    return out


def multi_region(
    regions: int,
    duration_s: float,
    mean_rate: float,
    amplitude: float = 0.8,
    isl: int = 256,
    osl: int = 24,
    num_groups: int = 16,
    seed: int = 0,
    ttft_target_s: float = 0.5,
    itl_target_s: float = 0.05,
) -> Dict[str, List[SimRequest]]:
    """Per-region diurnal traces with evenly phase-shifted peaks (follow-the-
    sun): when region 0 peaks, region k is 1/k of a period away. The merged
    fleet load is near-flat, which is what multi-pool balancing must hold."""
    period = duration_s / 2.0
    out: Dict[str, List[SimRequest]] = {}
    for r in range(regions):
        shift = period * r / max(regions, 1)
        items = sinusoidal_trace(
            duration_s=duration_s + shift, mean_rate=mean_rate,
            amplitude=amplitude, period_s=period, isl=isl, osl=osl,
            num_groups=num_groups, seed=seed + 1000 * r,
        )
        shifted = [
            TraceItem(it.t - shift, it.isl, it.osl, it.group)
            for it in items if it.t >= shift
        ]
        out[f"r{r}"] = _wrap(shifted, ttft_target_s=ttft_target_s,
                             itl_target_s=itl_target_s, region=f"r{r}")
    return out


def merge(*traces: List[SimRequest]) -> List[SimRequest]:
    """Interleave traces by arrival time (stable for equal stamps)."""
    flat = [req for tr in traces for req in tr]
    flat.sort(key=lambda r: r.t)
    return flat
