"""Guided-decoding grammars -> regex patterns.

JSON Schema is compiled to a regex (outlines-style: bounded constructs so
the result stays regular), then to a DFA by guided/regex.py. The
reference derives the same thing for guided_json and for forced
tool_choice (lib/llm/src/protocols/openai/common_ext.rs:180 "Tool-call
guided decoding ... derive guided_json from tool_choice").

Supported schema subset: type string (enum/const, minLength/maxLength),
integer, number, boolean, null, object (properties in declaration order;
non-required properties are emitted optionally), array (items,
minItems/maxItems, default 0..8), anyOf/oneOf, $ref -> $defs/definitions
(bounded expansion depth). Unknown/absent type falls back to a bounded
generic JSON value.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from .regex import escape_literal

WS = r"[ \n\t]{0,8}"  # bounded optional whitespace keeps the DFA small

STRING_RE = r'"([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})*"'
INTEGER_RE = r"-?(0|[1-9][0-9]*)"
NUMBER_RE = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"
BOOLEAN_RE = r"(true|false)"
NULL_RE = r"null"

DEFAULT_MAX_ITEMS = 8
DEFAULT_DEPTH = 3


def json_value_regex(depth: int = DEFAULT_DEPTH) -> str:
    """A generic JSON value with bounded NESTING — the grammar behind
    response_format {"type": "json_object"}. Repetition (array items,
    object members) is a `*` loop, not a bounded count: star re-enters the
    same sub-automaton, so the DFA stays small, while bounded depth is
    what keeps nested JSON regular at all."""
    scalar = f"({STRING_RE}|{NUMBER_RE}|{BOOLEAN_RE}|{NULL_RE})"
    value = scalar
    for _ in range(depth):
        arr = rf"\[{WS}({value}({WS},{WS}{value})*)?{WS}\]"
        obj = (
            rf"\{{{WS}({STRING_RE}{WS}:{WS}{value}"
            rf"({WS},{WS}{STRING_RE}{WS}:{WS}{value})*)?{WS}\}}"
        )
        value = f"({scalar}|{arr}|{obj})"
    return value


class SchemaError(ValueError):
    pass


def _string_regex(schema: Dict[str, Any]) -> str:
    if "pattern" in schema:
        # inner pattern constrains the CONTENT between the quotes; it must
        # itself avoid unescaped quotes to stay valid JSON. Parenthesized so
        # a top-level alternation cannot escape the quoting
        return f'"({schema["pattern"]})"'
    lo = schema.get("minLength")
    hi = schema.get("maxLength")
    if lo is not None or hi is not None:
        lo = int(lo or 0)
        ch = r'([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})'
        if hi is None:
            return f'"{ch}{{{lo},}}"'
        return f'"{ch}{{{lo},{int(hi)}}}"'
    return STRING_RE


def schema_to_regex(schema: Dict[str, Any], depth: int = 6) -> str:
    """Compile a JSON Schema (subset) to an anchored regex."""
    return _compile(schema, schema, depth)


def _compile(schema: Any, root: Any, depth: int) -> str:
    if depth < 0:
        raise SchemaError("schema nesting/$ref expansion too deep")
    if schema is True or schema == {}:
        return json_value_regex(2)
    if not isinstance(schema, dict):
        raise SchemaError(f"unsupported schema node: {schema!r}")

    if "$ref" in schema:
        ref = schema["$ref"]
        for prefix in ("#/$defs/", "#/definitions/"):
            if ref.startswith(prefix):
                name = ref[len(prefix):]
                defs = root.get(prefix.split("/")[1], {})
                if name not in defs:
                    raise SchemaError(f"unresolved $ref {ref}")
                return _compile(defs[name], root, depth - 1)
        raise SchemaError(f"unsupported $ref {ref} (only #/$defs, #/definitions)")

    if "const" in schema:
        return escape_literal(json.dumps(schema["const"]))
    if "enum" in schema:
        opts = "|".join(escape_literal(json.dumps(v)) for v in schema["enum"])
        return f"({opts})"
    if "anyOf" in schema or "oneOf" in schema:
        subs = schema.get("anyOf") or schema.get("oneOf")
        return "(" + "|".join(_compile(s, root, depth - 1) for s in subs) + ")"

    t = schema.get("type")
    if isinstance(t, list):
        return "(" + "|".join(
            _compile({**schema, "type": tt}, root, depth - 1) for tt in t
        ) + ")"
    if t == "string":
        return _string_regex(schema)
    if t == "integer":
        return INTEGER_RE
    if t == "number":
        return NUMBER_RE
    if t == "boolean":
        return BOOLEAN_RE
    if t == "null":
        return NULL_RE
    if t == "array":
        item = _compile(schema.get("items", {}), root, depth - 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if hi is None:
            # unbounded: star keeps the automaton size linear in the item
            body = f"{item}({WS},{WS}{item})*"
            if lo > 1:
                body = f"{item}({WS},{WS}{item}){{{lo - 1},}}"
        else:
            hi = int(hi)
            if hi < lo:
                raise SchemaError("maxItems < minItems")
            if hi == 0:
                return rf"\[{WS}\]"
            body = f"{item}({WS},{WS}{item}){{{max(lo - 1, 0)},{hi - 1}}}"
        if lo == 0:
            return rf"\[{WS}({body})?{WS}\]"
        return rf"\[{WS}{body}{WS}\]"
    if t == "object":
        props: Dict[str, Any] = schema.get("properties", {})
        if not props:
            return json_value_regex(2)
        required = set(schema.get("required", list(props)))
        parts: List[tuple] = []
        for name, sub in props.items():
            val = _compile(sub, root, depth - 1)
            pair = f'{escape_literal(json.dumps(name))}{WS}:{WS}{val}'
            parts.append((pair, name in required))
        # Emission order = declaration order. Required props are joined by
        # commas; each optional prop rides with the comma that its position
        # needs. To keep the regex REGULAR and simple we emit optionals as
        # (pair ,)? BEFORE the next required, and (, pair)? after the last
        # required — standard outlines-style approximation.
        req = [p for p, r in parts if r]
        opt = [p for p, r in parts if not r]
        if req:
            body = f"{WS},{WS}".join(req)
            for p in opt:
                body = body + f"({WS},{WS}{p})?"
        else:
            # all optional: any non-empty subset in declaration order, comma-
            # separated. One alternative per possible FIRST property (which
            # carries no leading comma), each followed by the later ones as
            # optional comma-led tails — O(n^2) pattern, not 2^n.
            alts = [
                opt[i] + "".join(f"({WS},{WS}{p})?" for p in opt[i + 1:])
                for i in range(len(opt))
            ]
            body = "(" + "|".join(alts) + ")?" if alts else ""
        return rf"\{{{WS}{body}{WS}\}}"
    # no/unknown type
    return json_value_regex(2)


def choice_regex(choices: List[str]) -> str:
    """guided_choice: exactly one of the given strings."""
    if not choices:
        raise SchemaError("guided_choice requires a non-empty list")
    return "(" + "|".join(escape_literal(c) for c in choices) + ")"


def guided_regex_pattern(kind: str, value: Any) -> str:
    """Normalize a guided spec {kind, value} to one anchored pattern.

    kinds: regex (value = pattern), choice (list of strings), json
    (schema dict or JSON string), json_object (None)."""
    if kind == "regex":
        if not isinstance(value, str):
            raise SchemaError("guided_regex takes a pattern string")
        return value
    if kind == "choice":
        return choice_regex(list(value))
    if kind == "json":
        schema = json.loads(value) if isinstance(value, str) else value
        if not isinstance(schema, (dict, bool)):
            raise SchemaError("guided_json takes a schema object")
        return schema_to_regex(schema)
    if kind == "json_object":
        return json_value_regex()
    raise SchemaError(f"unknown guided kind {kind!r}")
