"""DFA x tokenizer-vocabulary product tables for on-device guided masking.

The byte-level DFA (guided/regex.py) is lifted to TOKEN granularity: for
every DFA state s and token t, walking t's bytes from s either rejects or
lands in a state — a [S, V] table. Stored compressed for the device:

- tokens with identical transition COLUMNS collapse into classes:
  ``class_of`` [V] int32 and ``trans`` [S, C] int32 (-1 = reject). C is
  small (tokens inside a JSON string mostly behave identically), so the
  per-slot device cost is one [V] class map + one [S, C] table instead of
  [S, V].
- EOS is its own class: allowed exactly at accepting states (emitting EOS
  finishes the constrained text); all other special tokens are rejected
  everywhere. At accepting DEAD-END states (match complete, no byte can
  extend it) EOS is the only allowed class, which forces termination.

The engine gathers ``trans[state]`` -> [C] and indexes it by ``class_of``
to mask logits each step, then steps the state with the sampled token —
all inside the jitted decode programs (engine/engine.py), so guided rows
ride the normal decode horizons with zero host round-trips.

The vectorized product walk processes all (state, token) pairs one byte
position at a time with numpy gathers, so cost is O(max_token_len) table
gathers, not a Python loop over V*S.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .regex import Dfa


@dataclasses.dataclass
class TokenTables:
    """Compressed token-level automaton for one grammar x one vocabulary."""

    class_of: np.ndarray      # [V] int32 token -> class
    trans: np.ndarray         # [S, C] int32 next state or -1
    accept: np.ndarray        # [S] bool (accepting byte-states)
    eos_id: int

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    @property
    def num_classes(self) -> int:
        return self.trans.shape[1]

    def allowed(self, state: int) -> np.ndarray:
        """[V] bool mask of tokens legal from ``state`` (host-side view)."""
        return self.trans[state][self.class_of] >= 0

    def step(self, state: int, token: int) -> int:
        """Host-side replay of the device transition (engine resync after a
        horizon is applied)."""
        nxt = int(self.trans[state, self.class_of[token]])
        if nxt < 0:
            raise ValueError(f"token {token} not allowed from state {state}")
        return nxt

    def walk(self, state: int, tokens: Sequence[int]) -> int:
        for t in tokens:
            state = self.step(state, t)
        return state


def build_token_tables(
    dfa: Dfa,
    vocab: List[Optional[bytes]],
    eos_id: int,
) -> TokenTables:
    """Product-construct the token tables.

    ``vocab[t]`` is token t's exact byte contribution, or None for special/
    untextual tokens (rejected everywhere). ``eos_id`` is handled per the
    module docstring."""
    S = dfa.num_states
    V = len(vocab)
    maxlen = max((len(b) for b in vocab if b), default=1)

    # byte matrix [V, maxlen] padded with -1
    bytes_mat = np.full((V, maxlen), -1, np.int32)
    lens = np.zeros(V, np.int32)
    special = np.zeros(V, bool)
    for t, b in enumerate(vocab):
        if b is None:
            special[t] = True
            continue
        if len(b) == 0:
            # zero-byte tokens would self-loop without consuming grammar:
            # reject them under guidance
            special[t] = True
            continue
        lens[t] = len(b)
        bytes_mat[t, : len(b)] = np.frombuffer(b, np.uint8).astype(np.int32)

    # full product [S, V]: iterate byte positions, gathering through the DFA
    state = np.broadcast_to(
        np.arange(S, dtype=np.int32)[:, None], (S, V)
    ).copy()
    for p in range(maxlen):
        col = bytes_mat[:, p]                      # [V]
        active = (col >= 0)[None, :] & (state >= 0)  # tokens this long, alive
        idx_state = np.where(state >= 0, state, 0)
        nxt = dfa.trans[idx_state, np.clip(col, 0, 255)[None, :]]
        state = np.where(active, nxt, state)
    full = np.where(special[None, :], -1, state)   # [S, V] int32
    full[:, eos_id] = np.where(dfa.accept, _EOS_SENTINEL, -1)

    # compress identical columns into classes
    cols = np.ascontiguousarray(full.T)            # [V, S]
    uniq, inverse = np.unique(cols, axis=0, return_inverse=True)
    class_of = inverse.astype(np.int32)
    trans = np.ascontiguousarray(uniq.T).astype(np.int32)  # [S, C]
    return TokenTables(
        class_of=class_of, trans=trans, accept=dfa.accept.copy(),
        eos_id=eos_id,
    )


# EOS "next state" sentinel: after EOS the engine stops; any valid state id
# works. Use 0 so the table stays within [0, S).
_EOS_SENTINEL = 0


# --------------------------------------------------- vocabulary byte forms


def vocab_bytes_from_tokenizer(tok) -> Tuple[List[Optional[bytes]], int]:
    """(vocab byte forms, eos_id) for a framework tokenizer
    (llm/tokenizer.py ByteTokenizer / HFTokenizer): the exact byte
    contribution per token id.

    - byte tokenizer: id == byte value; specials (>=256) map to None.
    - HF tokenizers: GPT-2 byte-level alphabet decoded per token piece
      (Ġ -> space etc.); SentencePiece-style pieces handle ▁ and <0xXX>
      byte-fallback forms. Special tokens map to None (rejected under
      guidance)."""
    eos_id = getattr(tok, "eos_token_id", None)
    hf = getattr(tok, "_tok", None)  # HFTokenizer wraps transformers here
    if hf is None:
        # byte-level tokenizer: ids 0-255 are literal bytes
        size = int(getattr(tok, "vocab_size", 512))
        out: List[Optional[bytes]] = [
            bytes([i]) if i < 256 else None for i in range(size)
        ]
        return out, int(eos_id if eos_id is not None else 257)

    size = len(hf)
    specials = set(getattr(hf, "all_special_ids", []) or [])
    byte_decoder = _gpt2_byte_decoder()
    out = []
    for i in range(size):
        if i in specials:
            out.append(None)
            continue
        piece = hf.convert_ids_to_tokens(i)
        if piece is None:
            out.append(None)
            continue
        out.append(_piece_bytes(piece, byte_decoder))
    if eos_id is None:
        eos_id = getattr(hf, "eos_token_id", None)
    if eos_id is None:
        raise ValueError("tokenizer has no EOS id; guided decoding needs one")
    return out, int(eos_id)


def _piece_bytes(piece: str, byte_decoder: Dict[str, int]) -> Optional[bytes]:
    # SentencePiece byte-fallback tokens: "<0x0A>"
    if len(piece) == 6 and piece.startswith("<0x") and piece.endswith(">"):
        try:
            return bytes([int(piece[3:5], 16)])
        except ValueError:
            pass
    # GPT-2 byte-level alphabet: every char maps back to one byte
    if all(c in byte_decoder for c in piece):
        return bytes(byte_decoder[c] for c in piece)
    # SentencePiece visible-space convention
    return piece.replace("▁", " ").encode("utf-8")


def _gpt2_byte_decoder() -> Dict[str, int]:
    """The byte<->unicode alphabet used by GPT-2-style byte-level BPE
    (public construction: printable bytes map to themselves, the rest to
    U+0100.. offsets)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}
