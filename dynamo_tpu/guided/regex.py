"""Byte-level regex -> DFA compiler for guided decoding.

The reference forwards guided_json/guided_regex/guided_choice to engines
that constrain sampling with a compiled grammar (nvext fields,
lib/llm/src/protocols/openai/common_ext.rs:175-219; GuidedDecodingOptions,
lib/llm/src/protocols/common.rs:336). This framework owns its engine, so
the compiler lives here: a regex subset is parsed to an NFA (Thompson
construction) and determinized (subset construction) over a byte alphabet
partitioned into equivalence classes, producing a dense DFA transition
table the token layer (guided/tokens.py) products against the tokenizer
vocabulary.

Byte-level semantics: patterns match UTF-8 BYTES. ASCII classes work as
expected; `.` additionally admits non-ASCII continuation bytes so UTF-8
text flows through. This is the outlines/xgrammar-style approximation —
sound for constraining structure (JSON syntax, enums, numbers), which is
what guided decoding is for.

Supported syntax: literals, escapes (\\n \\t \\r \\\\ \\. etc), classes
[abc] [a-z0-9] [^...], ., \\d \\w \\s and negations, quantifiers * + ?
{m} {m,} {m,n}, alternation |, groups (). Anchored fullmatch semantics
(like re.fullmatch).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np


class RegexError(ValueError):
    pass


# ----------------------------------------------------------------- parsing
# AST: ("lit", frozenset[int]) | ("cat", [..]) | ("alt", [..])
#      | ("star", node) | ("plus", node) | ("opt", node) | ("eps",)

_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F]
)
_SPACE = frozenset([0x20, 0x09, 0x0A, 0x0D, 0x0B, 0x0C])
_ALL = frozenset(range(256))
# `.`: any byte except newline; includes 0x80-0xFF so UTF-8 payload bytes
# inside strings are representable
_DOT = _ALL - frozenset([0x0A])

_ESCAPES = {
    "n": frozenset([0x0A]), "t": frozenset([0x09]), "r": frozenset([0x0D]),
    "f": frozenset([0x0C]), "v": frozenset([0x0B]), "0": frozenset([0x00]),
    "d": _DIGITS, "D": _ALL - _DIGITS,
    "w": _WORD, "W": _ALL - _WORD,
    "s": _SPACE, "S": _ALL - _SPACE,
}


class _Parser:
    def __init__(self, pattern: str):
        self.b = pattern.encode("utf-8")
        self.i = 0

    def peek(self) -> Optional[int]:
        return self.b[self.i] if self.i < len(self.b) else None

    def next(self) -> int:
        if self.i >= len(self.b):
            raise RegexError("unexpected end of pattern")
        c = self.b[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self.alt()
        if self.i != len(self.b):
            raise RegexError(f"unbalanced pattern at byte {self.i}")
        return node

    def alt(self):
        branches = [self.cat()]
        while self.peek() == 0x7C:  # |
            self.next()
            branches.append(self.cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def cat(self):
        parts = []
        while True:
            c = self.peek()
            if c is None or c in (0x7C, 0x29):  # | )
                break
            parts.append(self.repeat())
        if not parts:
            return ("eps",)
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def repeat(self):
        node = self.atom()
        while True:
            c = self.peek()
            if c == 0x2A:    # *
                self.next(); node = ("star", node)
            elif c == 0x2B:  # +
                self.next(); node = ("plus", node)
            elif c == 0x3F:  # ?
                self.next(); node = ("opt", node)
            elif c == 0x7B:  # {m,n}
                node = self.bounded(node)
            else:
                return node

    def bounded(self, node):
        self.next()  # {
        lo = self._int()
        hi = lo
        if self.peek() == 0x2C:  # ,
            self.next()
            hi = self._int() if self.peek() != 0x7D else None
        if self.next() != 0x7D:
            raise RegexError("expected }")
        if hi is not None and hi < lo:
            raise RegexError("bad {m,n} bounds")
        parts = [node] * lo
        if hi is None:
            parts.append(("star", node))
        else:
            parts.extend([("opt", node)] * (hi - lo))
        if not parts:
            return ("eps",)
        return parts[0] if len(parts) == 1 else ("cat", parts)

    # {m,n} expansion materializes m+n AST copies and as many NFA states, so
    # an uncapped user-supplied count is an allocation bomb at PARSE time
    # (validate_pattern runs on the frontend event loop)
    MAX_REPEAT = 4096

    def _int(self) -> int:
        ds = []
        while self.peek() is not None and 0x30 <= self.peek() <= 0x39:
            ds.append(self.next() - 0x30)
        if not ds:
            raise RegexError("expected integer in {}")
        v = 0
        for d in ds:
            v = v * 10 + d
            if v > self.MAX_REPEAT:
                raise RegexError(
                    f"repetition count exceeds {self.MAX_REPEAT}"
                )
        return v

    def atom(self):
        c = self.next()
        if c == 0x28:  # (
            # non-capturing group marker (?: accepted and ignored
            if self.peek() == 0x3F:
                self.next()
                if self.next() != 0x3A:
                    raise RegexError("only (?: groups supported")
            node = self.alt()
            if self.next() != 0x29:
                raise RegexError("expected )")
            return node
        if c == 0x5B:  # [
            return ("lit", self.char_class())
        if c == 0x2E:  # .
            return ("lit", _DOT)
        if c == 0x5C:  # backslash
            return ("lit", self.escape())
        if c in (0x2A, 0x2B, 0x3F, 0x7B, 0x7D, 0x29):
            raise RegexError(f"unexpected {chr(c)!r}")
        return ("lit", frozenset([c]))

    def escape(self) -> FrozenSet[int]:
        c = self.next()
        ch = chr(c)
        if ch in _ESCAPES:
            return _ESCAPES[ch]
        if ch == "x":
            h = chr(self.next()) + chr(self.next())
            return frozenset([int(h, 16)])
        return frozenset([c])  # escaped literal (\. \[ \\ ...)

    def char_class(self) -> FrozenSet[int]:
        negate = False
        if self.peek() == 0x5E:  # ^
            self.next()
            negate = True
        members: Set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexError("unterminated [...]")
            if c == 0x5D and not first:  # ]
                self.next()
                break
            first = False
            c = self.next()
            if c == 0x5C:
                s = self.escape()
                if len(s) > 1:  # \d etc inside class
                    members |= s
                    continue
                c = next(iter(s))
            # range a-b (a lone trailing - is a literal)
            if self.peek() == 0x2D and self.i + 1 < len(self.b) and self.b[self.i + 1] != 0x5D:
                self.next()
                hi = self.next()
                if hi == 0x5C:
                    s = self.escape()
                    if len(s) != 1:
                        raise RegexError("class range to multi-byte escape")
                    hi = next(iter(s))
                if hi < c:
                    raise RegexError("reversed class range")
                members |= set(range(c, hi + 1))
            else:
                members.add(c)
        return frozenset(_ALL - members) if negate else frozenset(members)


# ----------------------------------------------------- NFA (Thompson) -> DFA


class _Nfa:
    """States are ints; transitions state -> [(byteset, state)]; eps edges
    separate. One start, one accept (Thompson invariant).

    ``max_states`` caps the BUILD, not just the later subset construction:
    nested bounded repeats multiply through shared AST nodes (parsing
    "((a{k}){k}){k}" is cheap, building its NFA is k^3), so an uncapped
    build is an allocation bomb that parse-time validation cannot see."""

    def __init__(self, max_states: int = 1 << 20):
        self.edges: List[List[Tuple[FrozenSet[int], int]]] = []
        self.eps: List[List[int]] = []
        self.max_states = max_states

    def new_state(self) -> int:
        if len(self.edges) >= self.max_states:
            raise RegexError(
                f"pattern expands past {self.max_states} NFA states; "
                "simplify nested repetitions"
            )
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1

    def build(self, node) -> Tuple[int, int]:
        kind = node[0]
        if kind == "eps":
            s = self.new_state(); a = self.new_state()
            self.eps[s].append(a)
            return s, a
        if kind == "lit":
            s = self.new_state(); a = self.new_state()
            self.edges[s].append((node[1], a))
            return s, a
        if kind == "cat":
            first_s, prev_a = self.build(node[1][0])
            for child in node[1][1:]:
                cs, ca = self.build(child)
                self.eps[prev_a].append(cs)
                prev_a = ca
            return first_s, prev_a
        if kind == "alt":
            s = self.new_state(); a = self.new_state()
            for child in node[1]:
                cs, ca = self.build(child)
                self.eps[s].append(cs)
                self.eps[ca].append(a)
            return s, a
        if kind == "star":
            cs, ca = self.build(node[1])
            s = self.new_state(); a = self.new_state()
            self.eps[s] += [cs, a]
            self.eps[ca] += [cs, a]
            return s, a
        if kind == "plus":
            cs, ca = self.build(node[1])
            s = self.new_state(); a = self.new_state()
            self.eps[s].append(cs)
            self.eps[ca] += [cs, a]
            return s, a
        if kind == "opt":
            cs, ca = self.build(node[1])
            s = self.new_state(); a = self.new_state()
            self.eps[s] += [cs, a]
            self.eps[ca].append(a)
            return s, a
        raise RegexError(f"unknown node {kind}")


@dataclasses.dataclass
class Dfa:
    """Dense byte-level DFA. trans[s, b] = next state or -1 (reject);
    accept[s] = True for match states. State 0 is the start."""

    trans: np.ndarray          # [S, 256] int32
    accept: np.ndarray         # [S] bool

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    def matches(self, data: bytes) -> bool:
        s = 0
        for b in data:
            s = int(self.trans[s, b])
            if s < 0:
                return False
        return bool(self.accept[s])

    def live(self, s: int) -> bool:
        """Any outgoing transition? False at dead-end accept states (match
        is complete — only EOS can follow)."""
        return bool((self.trans[s] >= 0).any())


def compile_regex(pattern: str, max_states: int = 32768) -> Dfa:
    """Parse + Thompson NFA + subset construction (over the partition of
    the byte alphabet induced by the NFA's edge sets, so determinization
    cost scales with distinct byte-classes, not 256)."""
    ast = _Parser(pattern).parse()
    nfa = _Nfa(max_states=max(1 << 16, 8 * max_states))
    start, accept = nfa.build(ast)

    # alphabet partition: bytes with identical edge membership everywhere
    sig = np.zeros(256, np.int64)
    seen: Dict[FrozenSet[int], int] = {}
    for es in nfa.edges:
        for byteset, _dst in es:
            if byteset not in seen:
                seen[byteset] = len(seen)
                arr = np.zeros(256, bool)
                arr[list(byteset)] = True
                # fold this set's membership into the per-byte signature
                sig = sig * 2 + arr.astype(np.int64)
                if len(seen) > 62:
                    # signature arithmetic would overflow int64: rehash
                    _, sig = np.unique(sig, return_inverse=True)
    _, byte_class = np.unique(sig, return_inverse=True)
    classes = [np.nonzero(byte_class == c)[0] for c in range(byte_class.max() + 1)]

    def closure(states: FrozenSet[int]) -> FrozenSet[int]:
        stack = list(states)
        out = set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = closure(frozenset([start]))
    dfa_ids: Dict[FrozenSet[int], int] = {start_set: 0}
    order = [start_set]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = np.full(256, -1, np.int32)
        for cls in classes:
            rep = int(cls[0])
            nxt = set()
            for s in cur:
                for byteset, dst in nfa.edges[s]:
                    if rep in byteset:
                        nxt.add(dst)
            if not nxt:
                continue
            nset = closure(frozenset(nxt))
            if nset not in dfa_ids:
                if len(dfa_ids) >= max_states:
                    raise RegexError(
                        f"DFA exceeds {max_states} states; simplify the "
                        "pattern or raise the limit"
                    )
                dfa_ids[nset] = len(dfa_ids)
                order.append(nset)
            row[cls] = dfa_ids[nset]
        rows.append(row)
    trans = np.stack(rows).astype(np.int32)
    acc = np.array([accept in st for st in order], bool)
    return _minimize(_trim_unproductive(Dfa(trans=trans, accept=acc)))


def _trim_unproductive(dfa: Dfa) -> Dfa:
    """Cut transitions into states from which no accept is reachable.

    Guarantees every reachable state offers SOME continuation (a byte
    transition or EOS-at-accept), which the engine's guided mask relies on:
    a state with nothing allowed would leave a row's logits all -inf.
    Possible sources: degenerate patterns like [^\\x00-\\xff] (empty class)."""
    trans, accept = dfa.trans, dfa.accept
    S = trans.shape[0]
    productive = accept.copy()
    while True:
        reach = productive[np.clip(trans, 0, S - 1)] & (trans >= 0)  # [S,256]
        new = productive | reach.any(axis=1)
        if np.array_equal(new, productive):
            break
        productive = new
    if bool(productive.all()):
        return dfa
    if not productive[0]:
        raise RegexError("pattern matches nothing")
    trans = np.where(
        (trans >= 0) & productive[np.clip(trans, 0, S - 1)], trans, -1
    ).astype(np.int32)
    return Dfa(trans=trans, accept=accept)


def _minimize(dfa: Dfa) -> Dfa:
    """Moore partition refinement. Thompson + subset construction leaves
    many equivalent states (a generic JSON grammar shrinks ~4x), and the
    DFA state count directly sizes the per-slot device tables in
    guided/tokens.py, so minimization pays for itself."""
    trans, accept = dfa.trans, dfa.accept
    S = trans.shape[0]
    labels = accept.astype(np.int64)
    for _ in range(S):
        # signature: own label + the label of each byte-successor
        # (-1 reject successors keep label -1)
        succ = np.where(trans >= 0, labels[np.clip(trans, 0, S - 1)], -1)
        sig = np.concatenate([labels[:, None], succ], axis=1)
        _, new = np.unique(sig, axis=0, return_inverse=True)
        if np.array_equal(new, labels):
            break
        labels = new.astype(np.int64)
    n = int(labels.max()) + 1
    if n == S:
        return dfa
    # representative state per class; start state (0) must stay class... 0
    # is wherever its class lands — remap so class-of-start is index 0
    perm = np.full(n, -1, np.int64)
    order_ids = np.empty(n, np.int64)
    nxt = 0
    for s in range(S):
        c = labels[s]
        if perm[c] < 0:
            perm[c] = nxt
            order_ids[nxt] = s
            nxt += 1
    new_labels = perm[labels]
    rep = order_ids[:n]
    small = trans[rep]                                  # [n, 256]
    small = np.where(small >= 0, new_labels[np.clip(small, 0, S - 1)], -1)
    return Dfa(
        trans=small.astype(np.int32), accept=accept[rep].copy()
    )


def validate_pattern(pattern: str) -> None:
    """Syntax-check a pattern without building the DFA (frontends reject
    malformed grammars as 400s before the request reaches an engine; the
    engine still enforces its own state/class caps at compile time)."""
    _Parser(pattern).parse()


def escape_literal(s: str) -> str:
    """Escape a literal string for embedding in a pattern."""
    out = []
    for ch in s:
        if ch in ".[]{}()*+?|\\^$-":
            out.append("\\" + ch)
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\t":
            out.append("\\t")
        elif ch == "\r":
            out.append("\\r")
        else:
            out.append(ch)
    return "".join(out)
