"""Guided (grammar-constrained) decoding.

Reference parity: nvext guided_json / guided_regex / guided_choice /
response_format json_schema|json_object, forwarded per request and
enforced during sampling (lib/llm/src/protocols/openai/common_ext.rs:
175-219, lib/llm/src/protocols/common.rs:336 GuidedDecodingOptions).

TPU-native shape: grammar -> byte DFA (regex.py) -> token-class-compressed
tables (tokens.py) that live on device and are applied INSIDE the jitted
decode programs — the FSM state rides the decode-horizon scan carry, so
constrained rows keep full horizon pipelining (no per-token host sync).
"""

from .regex import Dfa, RegexError, compile_regex, escape_literal
from .schema import (
    SchemaError,
    choice_regex,
    guided_regex_pattern,
    json_value_regex,
    schema_to_regex,
)
from .tokens import TokenTables, build_token_tables, vocab_bytes_from_tokenizer

__all__ = [
    "Dfa",
    "RegexError",
    "SchemaError",
    "TokenTables",
    "build_token_tables",
    "choice_regex",
    "compile_regex",
    "escape_literal",
    "guided_regex_pattern",
    "json_value_regex",
    "schema_to_regex",
    "vocab_bytes_from_tokenizer",
]
