"""Streaming reasoning + tool-call parsers.

TPU-framework analog of the reference's dynamo-parsers crate
(lib/parsers/src: reasoning/{base,gpt_oss,granite}, tool_calling/
{json,pythonic,harmony,dsml,xml}) and the chat-completions "jail" that holds
back partial matches (lib/llm/src/protocols/openai/chat_completions/jail.rs).

Everything is incremental: parsers consume text deltas as they stream off the
detokenizer and emit (content, reasoning_content, tool_calls) events, holding
back only the minimal suffix that might still become a marker.
"""

from .jail import HoldBack, split_safe
from .reasoning import ReasoningParser, get_reasoning_parser
from .tool_calls import (
    JsonToolParser,
    PythonicToolParser,
    XmlToolParser,
    get_tool_parser,
)

__all__ = [
    "HoldBack",
    "split_safe",
    "ReasoningParser",
    "get_reasoning_parser",
    "JsonToolParser",
    "PythonicToolParser",
    "XmlToolParser",
    "get_tool_parser",
]
