"""Marker hold-back ("jail"): never stream text that might still turn out to
be the start of a marker (stop sequence, tool-call tag, reasoning tag).

Analog of the reference's chat-completions jail
(lib/llm/src/protocols/openai/chat_completions/jail.rs), which buffers SSE
deltas while a partial tool-call or stop-sequence match is possible.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple


def split_safe(buf: str, markers: Sequence[str]) -> Tuple[str, str]:
    """Split ``buf`` into (safe, held): ``held`` is the longest suffix of
    ``buf`` that is a proper prefix of any marker (and so must be withheld
    until more text arrives)."""
    max_hold = 0
    for m in markers:
        # longest suffix of buf that is a prefix of m
        limit = min(len(buf), len(m) - 1)
        for k in range(limit, max_hold, -1):
            if buf.endswith(m[:k]):
                max_hold = k
                break
    if max_hold == 0:
        return buf, ""
    return buf[:-max_hold], buf[-max_hold:]


class DropMarkers:
    """Incrementally delete exact marker strings from a stream (e.g. gpt-oss
    channel headers that must not reach the client), holding back partial
    matches at chunk boundaries."""

    def __init__(self, markers: Sequence[str]):
        self.markers = sorted((m for m in markers if m), key=len, reverse=True)
        self._buf = ""

    def feed(self, text: str) -> str:
        self._buf += text
        for m in self.markers:
            self._buf = self._buf.replace(m, "")
        safe, self._buf = split_safe(self._buf, self.markers)
        return safe

    def flush(self) -> str:
        held, self._buf = self._buf, ""
        return held


class HoldBack:
    """Incremental wrapper over split_safe: feed deltas, get safe text out;
    flush() releases whatever is still held at end-of-stream."""

    def __init__(self, markers: Sequence[str]):
        self.markers: List[str] = [m for m in markers if m]
        self._held = ""

    def feed(self, text: str) -> str:
        buf = self._held + text
        safe, self._held = split_safe(buf, self.markers)
        return safe

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held
