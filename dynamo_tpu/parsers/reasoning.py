"""Streaming reasoning parsers: split model output into reasoning_content vs
content, incrementally.

Analog of the reference's reasoning parsers (lib/parsers/src/reasoning/:
base_parser for <think>-style tags, gpt_oss channel parser, granite
response-tag parser). Tag-based models are covered by ``ReasoningParser``
with per-model tag config; ``force_reasoning`` handles models (deepseek-r1
style) that open in reasoning mode without emitting the open tag.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from .jail import DropMarkers, split_safe


@dataclasses.dataclass
class ReasoningEvent:
    content: str = ""
    reasoning: str = ""


class ReasoningParser:
    """Incremental <open>...</close> splitter with partial-tag hold-back."""

    def __init__(
        self,
        open_tag: str = "<think>",
        close_tag: str = "</think>",
        force_reasoning: bool = False,
        content_filters: Tuple[str, ...] = (),
    ):
        self.open_tag = open_tag
        self.close_tag = close_tag
        self._state = "reasoning" if force_reasoning else "content"
        self._buf = ""
        self._dropper = DropMarkers(content_filters) if content_filters else None

    def feed(self, text: str) -> ReasoningEvent:
        ev = self._feed(text)
        if self._dropper is not None:
            ev.content = self._dropper.feed(ev.content)
        return ev

    def _feed(self, text: str) -> ReasoningEvent:
        self._buf += text
        ev = ReasoningEvent()
        while True:
            if self._state == "content":
                idx = self._buf.find(self.open_tag)
                if idx >= 0:
                    ev.content += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.open_tag):]
                    self._state = "reasoning"
                    continue
                safe, held = split_safe(self._buf, [self.open_tag])
                ev.content += safe
                self._buf = held
                return ev
            else:
                idx = self._buf.find(self.close_tag)
                if idx >= 0:
                    ev.reasoning += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.close_tag):]
                    # models usually emit "\n\n" right after </think>
                    self._state = "content"
                    continue
                safe, held = split_safe(self._buf, [self.close_tag])
                ev.reasoning += safe
                self._buf = held
                return ev

    def flush(self) -> ReasoningEvent:
        held, self._buf = self._buf, ""
        if self._state != "content":
            return ReasoningEvent(reasoning=held)
        if self._dropper is not None:
            held = self._dropper.feed(held) + self._dropper.flush()
        return ReasoningEvent(content=held)


_REGISTRY = {
    # name -> constructor kwargs (reference: parser selection by model family)
    "deepseek_r1": dict(open_tag="<think>", close_tag="</think>", force_reasoning=True),
    "qwen3": dict(open_tag="<think>", close_tag="</think>"),
    "think": dict(open_tag="<think>", close_tag="</think>"),
    "granite": dict(
        open_tag="Here is my thought process:", close_tag="Here is my response:"
    ),
    "gpt_oss": dict(
        open_tag="<|channel|>analysis<|message|>", close_tag="<|end|>",
        # final-channel headers/terminators are plumbing, not content
        content_filters=(
            "<|start|>assistant<|channel|>final<|message|>",
            "<|channel|>final<|message|>",
            "<|start|>assistant",
            "<|return|>",
            "<|end|>",
        ),
    ),
}


def get_reasoning_parser(name: Optional[str]) -> Optional[ReasoningParser]:
    if not name or name == "none":
        return None
    try:
        return ReasoningParser(**_REGISTRY[name])
    except KeyError:
        raise ValueError(
            f"unknown reasoning parser {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
