"""Streaming tool-call parsers.

Analog of the reference's tool_calling parsers (lib/parsers/src/tool_calling/:
json, pythonic, xml/dsml, harmony). Each parser consumes text deltas, passes
non-tool content through (with minimal hold-back while a marker prefix is
possible), and emits complete OpenAI-shape tool calls:

    {"id": "call_<n>", "type": "function",
     "function": {"name": str, "arguments": json-string}}

Completed calls are emitted as soon as their closing marker parses (streamed
per-call, like the reference jail releasing a held tool call).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import uuid
from typing import Any, Dict, List, Optional

from .jail import split_safe


@dataclasses.dataclass
class ToolEvent:
    content: str = ""
    tool_calls: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


def _mk_call(name: str, arguments: Any) -> Dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


class _TagToolParser:
    """Shared machinery for parsers whose tool calls sit between an open and
    a close tag; subclasses parse the captured body."""

    open_tag = ""
    close_tag = ""

    def __init__(self) -> None:
        self._buf = ""
        self._in_call = False

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def feed(self, text: str) -> ToolEvent:
        self._buf += text
        ev = ToolEvent()
        while True:
            if not self._in_call:
                idx = self._buf.find(self.open_tag)
                if idx >= 0:
                    ev.content += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.open_tag):]
                    self._in_call = True
                    continue
                safe, held = split_safe(self._buf, [self.open_tag])
                ev.content += safe
                self._buf = held
                return ev
            idx = self._buf.find(self.close_tag)
            if idx < 0:
                return ev  # wait for the close tag
            body = self._buf[:idx]
            self._buf = self._buf[idx + len(self.close_tag):]
            self._in_call = False
            try:
                ev.tool_calls.extend(self._parse_body(body))
            except Exception:
                # malformed call: surface the raw text instead of dropping it
                ev.content += self.open_tag + body + self.close_tag
            # swallow a single newline separating consecutive tool calls
            if self._buf.startswith("\n"):
                self._buf = self._buf[1:]

    def flush(self) -> ToolEvent:
        held, self._buf = self._buf, ""
        if self._in_call:
            self._in_call = False
            return ToolEvent(content=self.open_tag + held)
        return ToolEvent(content=held)


class JsonToolParser(_TagToolParser):
    """Hermes/Qwen style: <tool_call>{"name": ..., "arguments": {...}}</tool_call>."""

    open_tag = "<tool_call>"
    close_tag = "</tool_call>"

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        obj = json.loads(body)
        calls = obj if isinstance(obj, list) else [obj]
        out = []
        for c in calls:
            out.append(
                _mk_call(c["name"], c.get("arguments", c.get("parameters", {})))
            )
        return out


class XmlToolParser(_TagToolParser):
    """<function=name><parameter=key>value</parameter>...</function> style
    (reference: tool_calling/dsml + xml parsers)."""

    open_tag = "<function="
    close_tag = "</function>"
    _param_re = re.compile(
        r"<parameter=([^>]+)>(.*?)</parameter>", re.DOTALL
    )

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        name, sep, rest = body.partition(">")
        if not sep:
            raise ValueError("unterminated function tag")
        args = {}
        for key, value in self._param_re.findall(rest):
            value = value.strip()
            try:
                args[key] = json.loads(value)
            except Exception:
                args[key] = value
        return [_mk_call(name.strip(), args)]


class PythonicToolParser:
    """Llama-3.x pythonic style: the whole message is a list of calls, e.g.
    ``[get_weather(city="SF"), search(q="tpu", k=3)]``. Nothing can stream
    until the closing bracket; a message that does not look like a call list
    streams through untouched."""

    _head_re = re.compile(r"^\s*\[\s*[A-Za-z_][\w.]*\s*\(")

    def __init__(self) -> None:
        self._buf = ""
        self._decided: Optional[bool] = None  # None = still sniffing

    def feed(self, text: str) -> ToolEvent:
        self._buf += text
        if self._decided is None:
            if self._head_re.match(self._buf):
                self._decided = True
            elif len(self._buf) > 64 or (
                self._buf.strip() and not "[".startswith(self._buf.strip()[:1])
            ):
                self._decided = False
        if self._decided is False:
            out, self._buf = self._buf, ""
            return ToolEvent(content=out)
        if self._decided is True:
            calls = self._try_parse(self._buf)
            if calls is not None:
                self._buf = ""
                self._decided = None
                return ToolEvent(tool_calls=calls)
        return ToolEvent()

    def _try_parse(self, text: str) -> Optional[List[Dict[str, Any]]]:
        try:
            tree = ast.parse(text.strip(), mode="eval")
        except SyntaxError:
            return None
        if not isinstance(tree.body, ast.List):
            return None
        calls = []
        for node in tree.body.elts:
            if not isinstance(node, ast.Call):
                return None
            if node.args:
                # positional args can't be mapped to names without the tool
                # schema — fall back to raw text rather than dropping them
                return None
            name = ast.unparse(node.func)
            args: Dict[str, Any] = {}
            for kw in node.keywords:
                if kw.arg is None:
                    return None
                try:
                    args[kw.arg] = ast.literal_eval(kw.value)
                except Exception:
                    args[kw.arg] = ast.unparse(kw.value)
            calls.append(_mk_call(name, args))
        return calls

    def flush(self) -> ToolEvent:
        held, self._buf = self._buf, ""
        self._decided = None
        return ToolEvent(content=held)


class HarmonyToolParser:
    """gpt-oss harmony dialect (reference tool_calling/harmony/
    harmony_parser.rs): commentary-channel messages addressed to a
    ``functions.*`` recipient are tool calls —

        <|channel|>commentary to=functions.get_weather <|constrain|>json
        <|message|>{"location": "SF"}<|call|>

    analysis/final channels are the reasoning parser's business (gpt_oss
    entry in parsers/reasoning.py); this parser extracts only the
    tool-call messages and passes everything else through, holding back
    partial headers at chunk boundaries like every streaming parser here.
    """

    HEADER = "<|channel|>commentary to="
    MSG = "<|message|>"
    ENDS = ("<|call|>", "<|end|>", "<|return|>")

    def __init__(self) -> None:
        self._buf = ""

    def _try_parse_call(self) -> Optional[Dict[str, Any]]:
        """Parse one complete call at the head of ``_buf`` (which starts
        right after HEADER); returns the call and consumes it, or None if
        more text is needed (ValueError on malformed header)."""
        midx = self._buf.find(self.MSG)
        if midx < 0:
            return None
        header = self._buf[:midx]
        recipient = header.split()[0] if header.split() else ""
        if not recipient.startswith("functions."):
            # NOT consumed: the caller re-emits the header and the message
            # flows through as ordinary content
            raise ValueError(f"commentary recipient {recipient!r} is not a function")
        end_idx, end_len = -1, 0
        for e in self.ENDS:
            i = self._buf.find(e, midx + len(self.MSG))
            if i >= 0 and (end_idx < 0 or i < end_idx):
                end_idx, end_len = i, len(e)
        if end_idx < 0:
            return None
        args = self._buf[midx + len(self.MSG):end_idx]
        self._buf = self._buf[end_idx + end_len:]
        return _mk_call(recipient[len("functions."):], args.strip())

    def feed(self, text: str) -> ToolEvent:
        self._buf += text
        ev = ToolEvent()
        while True:
            idx = self._buf.find(self.HEADER)
            if idx < 0:
                safe, self._buf = split_safe(self._buf, [self.HEADER])
                ev.content += safe
                return ev
            head, self._buf = self._buf[:idx], self._buf[idx + len(self.HEADER):]
            try:
                call = self._try_parse_call()
            except ValueError:
                # commentary to a non-function recipient: emit it verbatim
                ev.content += head + self.HEADER
                continue
            if call is None:  # incomplete: restore and wait for more text
                self._buf = self.HEADER + self._buf
                ev.content += head
                return ev
            ev.content += head
            ev.tool_calls.append(call)

    def flush(self) -> ToolEvent:
        held, self._buf = self._buf, ""
        # end-of-stream may cut the terminator off a final call: accept a
        # message that parses as JSON even without <|call|>
        if held.startswith(self.HEADER):
            body = held[len(self.HEADER):]
            midx = body.find(self.MSG)
            if midx >= 0:
                recipient = body[:midx].split()[0] if body[:midx].split() else ""
                args = body[midx + len(self.MSG):].strip()
                if recipient.startswith("functions."):
                    try:
                        json.loads(args)
                        return ToolEvent(tool_calls=[
                            _mk_call(recipient[len("functions."):], args)
                        ])
                    except Exception:
                        pass
        return ToolEvent(content=held)


_REGISTRY = {
    "json": JsonToolParser,
    "hermes": JsonToolParser,
    "qwen": JsonToolParser,
    "pythonic": PythonicToolParser,
    "xml": XmlToolParser,
    "dsml": XmlToolParser,
    "harmony": HarmonyToolParser,
    "gpt_oss": HarmonyToolParser,
}


def get_tool_parser(name: Optional[str]):
    if not name or name == "none":
        return None
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown tool parser {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
