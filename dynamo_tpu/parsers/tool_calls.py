"""Streaming tool-call parsers.

Analog of the reference's tool_calling parsers (lib/parsers/src/tool_calling/:
json, pythonic, xml/dsml, harmony). Each parser consumes text deltas, passes
non-tool content through (with minimal hold-back while a marker prefix is
possible), and emits complete OpenAI-shape tool calls:

    {"id": "call_<n>", "type": "function",
     "function": {"name": str, "arguments": json-string}}

Completed calls are emitted as soon as their closing marker parses (streamed
per-call, like the reference jail releasing a held tool call).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import uuid
from typing import Any, Dict, List, Optional

from .jail import split_safe


@dataclasses.dataclass
class ToolEvent:
    content: str = ""
    tool_calls: List[Dict[str, Any]] = dataclasses.field(default_factory=list)


def _mk_call(name: str, arguments: Any) -> Dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


class _TagToolParser:
    """Shared machinery for parsers whose tool calls sit between an open and
    a close tag; subclasses parse the captured body."""

    open_tag = ""
    close_tag = ""

    def __init__(self) -> None:
        self._buf = ""
        self._in_call = False

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def feed(self, text: str) -> ToolEvent:
        self._buf += text
        ev = ToolEvent()
        while True:
            if not self._in_call:
                idx = self._buf.find(self.open_tag)
                if idx >= 0:
                    ev.content += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.open_tag):]
                    self._in_call = True
                    continue
                safe, held = split_safe(self._buf, [self.open_tag])
                ev.content += safe
                self._buf = held
                return ev
            idx = self._buf.find(self.close_tag)
            if idx < 0:
                return ev  # wait for the close tag
            body = self._buf[:idx]
            self._buf = self._buf[idx + len(self.close_tag):]
            self._in_call = False
            try:
                ev.tool_calls.extend(self._parse_body(body))
            except Exception:
                # malformed call: surface the raw text instead of dropping it
                ev.content += self.open_tag + body + self.close_tag
            # swallow a single newline separating consecutive tool calls
            if self._buf.startswith("\n"):
                self._buf = self._buf[1:]

    def flush(self) -> ToolEvent:
        held, self._buf = self._buf, ""
        if self._in_call:
            self._in_call = False
            return ToolEvent(content=self.open_tag + held)
        return ToolEvent(content=held)


class JsonToolParser(_TagToolParser):
    """Hermes/Qwen style: <tool_call>{"name": ..., "arguments": {...}}</tool_call>."""

    open_tag = "<tool_call>"
    close_tag = "</tool_call>"

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        obj = json.loads(body)
        calls = obj if isinstance(obj, list) else [obj]
        out = []
        for c in calls:
            out.append(
                _mk_call(c["name"], c.get("arguments", c.get("parameters", {})))
            )
        return out


class XmlToolParser(_TagToolParser):
    """<function=name><parameter=key>value</parameter>...</function> style
    (reference: tool_calling/dsml + xml parsers)."""

    open_tag = "<function="
    close_tag = "</function>"
    _param_re = re.compile(
        r"<parameter=([^>]+)>(.*?)</parameter>", re.DOTALL
    )

    def _parse_body(self, body: str) -> List[Dict[str, Any]]:
        name, sep, rest = body.partition(">")
        if not sep:
            raise ValueError("unterminated function tag")
        args = {}
        for key, value in self._param_re.findall(rest):
            value = value.strip()
            try:
                args[key] = json.loads(value)
            except Exception:
                args[key] = value
        return [_mk_call(name.strip(), args)]


class PythonicToolParser:
    """Llama-3.x pythonic style: the whole message is a list of calls, e.g.
    ``[get_weather(city="SF"), search(q="tpu", k=3)]``. Nothing can stream
    until the closing bracket; a message that does not look like a call list
    streams through untouched."""

    _head_re = re.compile(r"^\s*\[\s*[A-Za-z_][\w.]*\s*\(")

    def __init__(self) -> None:
        self._buf = ""
        self._decided: Optional[bool] = None  # None = still sniffing

    def feed(self, text: str) -> ToolEvent:
        self._buf += text
        if self._decided is None:
            if self._head_re.match(self._buf):
                self._decided = True
            elif len(self._buf) > 64 or (
                self._buf.strip() and not "[".startswith(self._buf.strip()[:1])
            ):
                self._decided = False
        if self._decided is False:
            out, self._buf = self._buf, ""
            return ToolEvent(content=out)
        if self._decided is True:
            calls = self._try_parse(self._buf)
            if calls is not None:
                self._buf = ""
                self._decided = None
                return ToolEvent(tool_calls=calls)
        return ToolEvent()

    def _try_parse(self, text: str) -> Optional[List[Dict[str, Any]]]:
        try:
            tree = ast.parse(text.strip(), mode="eval")
        except SyntaxError:
            return None
        if not isinstance(tree.body, ast.List):
            return None
        calls = []
        for node in tree.body.elts:
            if not isinstance(node, ast.Call):
                return None
            if node.args:
                # positional args can't be mapped to names without the tool
                # schema — fall back to raw text rather than dropping them
                return None
            name = ast.unparse(node.func)
            args: Dict[str, Any] = {}
            for kw in node.keywords:
                if kw.arg is None:
                    return None
                try:
                    args[kw.arg] = ast.literal_eval(kw.value)
                except Exception:
                    args[kw.arg] = ast.unparse(kw.value)
            calls.append(_mk_call(name, args))
        return calls

    def flush(self) -> ToolEvent:
        held, self._buf = self._buf, ""
        self._decided = None
        return ToolEvent(content=held)


_REGISTRY = {
    "json": JsonToolParser,
    "hermes": JsonToolParser,
    "qwen": JsonToolParser,
    "pythonic": PythonicToolParser,
    "xml": XmlToolParser,
    "dsml": XmlToolParser,
}


def get_tool_parser(name: Optional[str]):
    if not name or name == "none":
        return None
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown tool parser {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
