"""LoRA adapter sources + local cache.

Analog of the reference's LoRACache / LoRASource / LoRADownloader
(lib/llm/src/lora/{cache,source,downloader}.rs): adapters are fetched from a
source URI into a content-keyed local cache directory, then loaded as
per-layer weight stacks for the adapter table.

On-disk adapter format (TPU repack of the HF PEFT layout): one ``.npz``
with arrays ``<target>.A`` [L, in, r] and ``<target>.B`` [L, r, out], plus
optional scalars ``alpha`` and ``rank``. ``from_peft_dir`` converts a HF
PEFT checkpoint (adapter_model.safetensors + adapter_config.json) into this
layout so public adapters load directly.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Dict, Optional, Tuple

import numpy as np

from ..runtime.logging import get_logger

log = get_logger("lora.cache")


class LoRACache:
    """Content-keyed local cache directory (cache.rs analog)."""

    def __init__(self, root: Optional[str] = None):
        from ..runtime.config import ENV_LORA_CACHE

        self.root = root or os.environ.get(
            ENV_LORA_CACHE, os.path.expanduser("~/.cache/dynamo_tpu/lora")
        )
        os.makedirs(self.root, exist_ok=True)

    @staticmethod
    def uri_to_key(uri: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", uri.rstrip("/").rsplit("/", 1)[-1])
        digest = hashlib.sha256(uri.encode()).hexdigest()[:12]
        return f"{safe}-{digest}"

    def path_for(self, uri: str) -> str:
        return os.path.join(self.root, self.uri_to_key(uri) + ".npz")

    def is_cached(self, uri: str) -> bool:
        return os.path.exists(self.path_for(uri))


class LocalLoRASource:
    """file:// / plain-path source (source.rs LocalLoRASource analog; remote
    object-store sources plug in behind the same fetch(uri)->path surface)."""

    def fetch(self, uri: str, cache: LoRACache) -> str:
        path = uri[len("file://"):] if uri.startswith("file://") else uri
        if os.path.isdir(path):
            out = cache.path_for(uri)
            if not os.path.exists(out):
                from_peft_dir(path, out)
            return out
        if not os.path.exists(path):
            raise FileNotFoundError(f"lora adapter not found: {path}")
        if path.endswith(".npz"):
            out = cache.path_for(uri)
            if not os.path.exists(out):
                shutil.copyfile(path, out)
            return out
        raise ValueError(f"unsupported lora artifact {path!r} (need .npz or PEFT dir)")


def load_adapter(path: str) -> Tuple[Dict[str, np.ndarray], Optional[float]]:
    """.npz -> ({"<target>.A"/"<target>.B": array}, alpha)."""
    with np.load(path, allow_pickle=False) as z:
        weights = {k: z[k] for k in z.files if k.endswith((".A", ".B"))}
        alpha = float(z["alpha"]) if "alpha" in z.files else None
    if not weights:
        raise ValueError(f"{path}: no <target>.A/<target>.B arrays")
    return weights, alpha


_PEFT_NAME_MAP = {
    "q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo",
    "gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down",
}


def from_peft_dir(peft_dir: str, out_path: str) -> str:
    """Convert a HF PEFT adapter directory into the stacked .npz layout.

    Reads adapter_config.json (r, lora_alpha) and the safetensors/bin weight
    file with keys like
    ``base_model.model.model.layers.<i>.self_attn.q_proj.lora_A.weight``
    ([r, in] — transposed into [in, r] here; lora_B [out, r] -> [r, out])."""
    cfg_path = os.path.join(peft_dir, "adapter_config.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    alpha = float(cfg.get("lora_alpha", cfg.get("r", 16)))

    tensors: Dict[str, np.ndarray] = {}
    st_path = os.path.join(peft_dir, "adapter_model.safetensors")
    if os.path.exists(st_path):
        from safetensors.numpy import load_file

        tensors = load_file(st_path)
    else:
        import torch

        bin_path = os.path.join(peft_dir, "adapter_model.bin")
        for k, v in torch.load(bin_path, map_location="cpu", weights_only=True).items():
            tensors[k] = v.float().numpy()

    pat = re.compile(r"layers\.(\d+)\.(?:self_attn|mlp)\.(\w+)\.lora_([AB])\.weight")
    per: Dict[Tuple[str, str], Dict[int, np.ndarray]] = {}
    for key, w in tensors.items():
        m = pat.search(key)
        if not m:
            continue
        li, proj, ab = int(m.group(1)), m.group(2), m.group(3)
        tgt = _PEFT_NAME_MAP.get(proj)
        if tgt is None:
            continue
        per.setdefault((tgt, ab), {})[li] = np.asarray(w, np.float32)

    out: Dict[str, np.ndarray] = {"alpha": np.float32(alpha)}
    n_layers = 1 + max((max(d) for d in per.values()), default=0)
    for (tgt, ab), d in per.items():
        sample = next(iter(d.values()))
        stack = np.zeros((n_layers, *sample.shape), np.float32)
        for li, w in d.items():
            stack[li] = w
        if ab == "A":      # [L, r, in] -> [L, in, r]
            out[f"{tgt}.A"] = stack.transpose(0, 2, 1)
        else:              # [L, out, r] -> [L, r, out]
            out[f"{tgt}.B"] = stack.transpose(0, 2, 1)
    if len(out) <= 1:
        raise ValueError(f"{peft_dir}: no recognizable lora_A/lora_B tensors")
    np.savez(out_path, **out)
    log.info("converted PEFT adapter %s -> %s", peft_dir, out_path)
    return out_path
