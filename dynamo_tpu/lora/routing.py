"""LoRA fleet routing: rendezvous hashing + replica table.

Analogs of the reference's RendezvousHasher (lib/llm/src/lora/routing/
hrw.rs) and LoraRoutingTable (routing/table.rs): each adapter name maps to a
deterministic replica set of workers (highest-random-weight hashing, so
adding/removing workers only moves the minimal number of adapters), and the
frontend routes adapter requests within that set.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Dict, List, Optional, Sequence

from ..kv_router.protocols import WorkerWithDpRank


class RendezvousHasher:
    """HRW: score(name, worker) = blake2b(name || worker); top-k workers by
    score form the replica set (hrw.rs:12-40)."""

    @staticmethod
    def score(lora_name: str, worker: WorkerWithDpRank) -> int:
        h = hashlib.blake2b(
            f"{lora_name}|{worker.worker_id}|{worker.dp_rank}".encode(),
            digest_size=8,
        ).digest()
        return int.from_bytes(h, "big")

    @classmethod
    def rank_workers(
        cls, lora_name: str, workers: Sequence[WorkerWithDpRank]
    ) -> List[WorkerWithDpRank]:
        return sorted(workers, key=lambda w: cls.score(lora_name, w), reverse=True)

    @classmethod
    def replica_set(
        cls, lora_name: str, workers: Sequence[WorkerWithDpRank], replicas: int
    ) -> List[WorkerWithDpRank]:
        return cls.rank_workers(lora_name, workers)[: max(1, replicas)]


@dataclasses.dataclass
class LoraReplicaConfig:
    """One adapter's placement (table.rs:14-28)."""

    lora_name: str
    replicas: int = 1
    workers: List[WorkerWithDpRank] = dataclasses.field(default_factory=list)


class LoraRoutingTable:
    """name -> replica config; thread-safe (table.rs:30-85)."""

    def __init__(self):
        self._table: Dict[str, LoraReplicaConfig] = {}
        self._lock = threading.Lock()

    def update_allocation(self, name: str, config: LoraReplicaConfig) -> None:
        with self._lock:
            self._table[name] = config

    def get_replica_set(self, name: str) -> Optional[List[WorkerWithDpRank]]:
        with self._lock:
            cfg = self._table.get(name)
            return list(cfg.workers) if cfg else None

    def get_config(self, name: str) -> Optional[LoraReplicaConfig]:
        with self._lock:
            return self._table.get(name)

    def remove_lora(self, name: str) -> Optional[LoraReplicaConfig]:
        with self._lock:
            return self._table.pop(name, None)

    def list_loras(self) -> List[str]:
        with self._lock:
            return sorted(self._table)

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)

    def clear(self) -> None:
        with self._lock:
            self._table.clear()


def allocate(
    names: Sequence[str],
    workers: Sequence[WorkerWithDpRank],
    replicas: int = 1,
) -> LoraRoutingTable:
    """HRW allocation of every adapter onto the worker fleet (the reference's
    create_lora_allocator default path)."""
    table = LoraRoutingTable()
    for name in names:
        table.update_allocation(name, LoraReplicaConfig(
            lora_name=name, replicas=replicas,
            workers=RendezvousHasher.replica_set(name, workers, replicas),
        ))
    return table
