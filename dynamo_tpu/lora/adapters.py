"""Batched multi-LoRA: device-resident adapter tables, slot-indexed apply.

Where the reference hands LoRA to its engines (vLLM owns the math;
components/src/dynamo/vllm/main.py:712 load/unload endpoints), this framework
owns the model — so multi-adapter serving is designed for XLA:

- All adapters live in STACKED tables ``A[name]: [N, L, H, r]`` /
  ``B[name]: [N, L, r, out]`` allocated once at engine build with static
  shapes. Hot-loading adapter ``i`` is a functional ``.at[i].set`` rebind
  with unchanged shapes — zero recompiles; serving programs pick the new
  tables up at their next dispatch (tables are jit arguments, never
  constants).
- Per-request adapter selection is a gather: slot ``b`` uses
  ``A[ids[b]]``, so one decode batch mixes adapters freely (the S-LoRA /
  punica idea, expressed as plain einsums XLA fuses).
- id 0 is reserved as the no-adapter identity (zero tables), so base-model
  requests cost two zero-matmuls instead of a data-dependent branch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime.logging import get_logger

log = get_logger("lora")

# projection output sizes by target name, resolved from the model config
_TARGET_OUT = {
    "wq": lambda cfg: cfg.q_size,
    "wk": lambda cfg: cfg.kv_size,
    "wv": lambda cfg: cfg.kv_size,
    "wo": lambda cfg: cfg.hidden_size,
    "w_gate": lambda cfg: cfg.intermediate_size,
    "w_up": lambda cfg: cfg.intermediate_size,
    "w_down": lambda cfg: cfg.hidden_size,
}
_TARGET_IN = {
    "wq": lambda cfg: cfg.hidden_size,
    "wk": lambda cfg: cfg.hidden_size,
    "wv": lambda cfg: cfg.hidden_size,
    "wo": lambda cfg: cfg.q_size,
    "w_gate": lambda cfg: cfg.hidden_size,
    "w_up": lambda cfg: cfg.hidden_size,
    "w_down": lambda cfg: cfg.intermediate_size,
}


class LoraAdapterTable:
    """N-slot adapter store + name registry. Slot 0 = identity (no adapter)."""

    def __init__(
        self,
        model_cfg,
        max_adapters: int = 8,
        rank: int = 16,
        targets: Sequence[str] = ("wq", "wk", "wv", "wo"),
        dtype=jnp.bfloat16,
    ):
        for t in targets:
            if t not in _TARGET_OUT:
                raise ValueError(f"unknown LoRA target {t!r}")
        self.cfg = model_cfg
        self.max_adapters = max_adapters
        self.rank = rank
        self.targets = tuple(targets)
        self.dtype = dtype
        N, L, r = max_adapters + 1, model_cfg.num_layers, rank
        self.A: Dict[str, jax.Array] = {}
        self.B: Dict[str, jax.Array] = {}
        for t in targets:
            self.A[t] = jnp.zeros((N, L, _TARGET_IN[t](model_cfg), r), dtype)
            self.B[t] = jnp.zeros((N, L, r, _TARGET_OUT[t](model_cfg)), dtype)
        self.scales = jnp.zeros((N,), jnp.float32)
        self._names: List[Optional[str]] = [None] * N  # slot -> adapter name
        self._loading: Dict[str, int] = {}  # name -> reserved slot (in-flight)
        self._lock = threading.Lock()

    # -- registry ------------------------------------------------------------
    def slot_of(self, name: Optional[str]) -> int:
        """Adapter slot for a name; 0 (identity) when absent/None."""
        if not name:
            return 0
        with self._lock:
            try:
                return self._names.index(name)
            except ValueError:
                return 0

    def list_adapters(self) -> List[str]:
        with self._lock:
            return [n for n in self._names[1:] if n]

    # -- lifecycle -----------------------------------------------------------
    def load(
        self,
        name: str,
        weights: Dict[str, np.ndarray],
        alpha: Optional[float] = None,
    ) -> int:
        """Install adapter weights into a free slot (in-place device update —
        serving programs keep running). ``weights`` maps
        ``"<target>.A"``/``"<target>.B"`` to per-layer stacks [L, in, r] /
        [L, r, out]. Returns the slot id."""
        reserved = object()  # placeholder: slot taken, name not yet visible
        with self._lock:
            if name in self._names:
                slot = self._names.index(name)
            elif name in self._loading:
                # concurrent load of the same name reuses the reserved slot
                # (last writer wins on the tables; no second slot leaks)
                slot = self._loading[name]
            else:
                try:
                    slot = self._names.index(None, 1)
                except ValueError:
                    raise RuntimeError(
                        f"no free adapter slots (max {self.max_adapters})"
                    ) from None
                self._names[slot] = reserved  # type: ignore[assignment]
                self._loading[name] = slot
        # adapter rank = rank of the PROVIDED matrices (absent targets are
        # zero-filled at table rank and must not influence the scale)
        ranks = {
            weights[f"{t}.A"].shape[-1]
            for t in self.targets if f"{t}.A" in weights
        }
        r_eff = ranks.pop() if len(ranks) == 1 else self.rank
        try:
            for t in self.targets:
                a = weights.get(f"{t}.A")
                b = weights.get(f"{t}.B")
                if a is None or b is None:
                    # target absent in this adapter: identity (zeros)
                    a = np.zeros(self.A[t].shape[1:], np.float32)
                    b = np.zeros(self.B[t].shape[1:], np.float32)
                a, b = self._fit_rank(np.asarray(a), np.asarray(b))
                self.A[t] = self.A[t].at[slot].set(jnp.asarray(a, self.dtype))
                self.B[t] = self.B[t].at[slot].set(jnp.asarray(b, self.dtype))
            scale = (alpha if alpha is not None else float(r_eff)) / float(r_eff)
            self.scales = self.scales.at[slot].set(scale)
        except Exception:
            with self._lock:
                if self._loading.get(name) == slot:
                    del self._loading[name]
                    if not isinstance(self._names[slot], str):
                        self._names[slot] = None  # release the reserved slot
            raise
        # the name becomes routable only now, with every table written —
        # a request racing the load sees "unknown adapter", never zeros
        with self._lock:
            self._names[slot] = name
            self._loading.pop(name, None)
        log.info("lora adapter %r loaded into slot %d (scale %.3f)", name, slot, scale)
        return slot

    def _fit_rank(self, a: np.ndarray, b: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Pad (or reject) adapter rank into the static table rank."""
        r = a.shape[-1]
        if r > self.rank:
            raise ValueError(f"adapter rank {r} exceeds table rank {self.rank}")
        if r < self.rank:
            pad_a = np.zeros((*a.shape[:-1], self.rank - r), a.dtype)
            a = np.concatenate([a, pad_a], axis=-1)
            pad_b = np.zeros((*b.shape[:-2], self.rank - r, b.shape[-1]), b.dtype)
            b = np.concatenate([b, pad_b], axis=-2)
        return a, b

    def unload(self, name: str) -> bool:
        with self._lock:
            if name not in self._names:
                return False
            slot = self._names.index(name)
            self._names[slot] = None
        for t in self.targets:
            self.A[t] = self.A[t].at[slot].set(0.0)
            self.B[t] = self.B[t].at[slot].set(0.0)
        self.scales = self.scales.at[slot].set(0.0)
        log.info("lora adapter %r unloaded from slot %d", name, slot)
        return True

    # -- program inputs ------------------------------------------------------
    def tables(self) -> Dict[str, jax.Array]:
        """Flat dict handed into the jitted programs as arguments (never
        closure constants — tables mutate across loads)."""
        out: Dict[str, jax.Array] = {"scales": self.scales}
        for t in self.targets:
            out[f"{t}.A"] = self.A[t]
            out[f"{t}.B"] = self.B[t]
        return out


def make_lora_fn(tables: Dict[str, jax.Array], adapter_ids: jax.Array):
    """``lora(name, layer_idx, x) -> delta`` for llama.forward.

    adapter_ids: [B] int32 for batched decode ([B, S, H] activations), a
    scalar for single-sequence prefill ([S, H] activations), or [T] int32
    for a PACKED ragged buffer ([T, H] activations — the engine's mixed
    step, where each token carries its row's adapter index so one fused
    launch mixes adapters freely)."""
    scales = tables["scales"]
    per_token = getattr(adapter_ids, "ndim", 0) == 1

    def lora(name: str, layer_idx: int, x: jax.Array) -> Optional[jax.Array]:
        a_key, b_key = f"{name}.A", f"{name}.B"
        if a_key not in tables:
            return None
        A = tables[a_key][:, layer_idx]   # [N, in, r]
        Bm = tables[b_key][:, layer_idx]  # [N, r, out]
        if x.ndim == 2 and per_token:
            # packed buffer: one adapter id per TOKEN (punica-style
            # gathered batched LoRA, expressed as einsums XLA fuses)
            Atok = A[adapter_ids]             # [T, in, r]
            Btok = Bm[adapter_ids]            # [T, r, out]
            s = scales[adapter_ids][:, None]
            xa = jnp.einsum("th,thr->tr", x, Atok)
            return (jnp.einsum("tr,tro->to", xa, Btok) * s).astype(x.dtype)
        if x.ndim == 2:  # prefill: [S, H], one adapter
            s = scales[adapter_ids]
            xa = x @ A[adapter_ids]
            return ((xa @ Bm[adapter_ids]) * s).astype(x.dtype)
        # decode: [B, S, H], per-slot adapters
        Aslot = A[adapter_ids]            # [B, in, r]
        Bslot = Bm[adapter_ids]           # [B, r, out]
        s = scales[adapter_ids][:, None, None]
        xa = jnp.einsum("bsh,bhr->bsr", x, Aslot)
        return (jnp.einsum("bsr,bro->bso", xa, Bslot) * s).astype(x.dtype)

    return lora
