"""Multi-LoRA serving: device-resident adapter tables, cache/sources,
fleet routing (reference lib/llm/src/lora/)."""

from .adapters import LoraAdapterTable, make_lora_fn
from .cache import LoRACache, LocalLoRASource, from_peft_dir, load_adapter
from .routing import (
    LoraReplicaConfig,
    LoraRoutingTable,
    RendezvousHasher,
    allocate,
)

__all__ = [
    "LoraAdapterTable",
    "make_lora_fn",
    "LoRACache",
    "LocalLoRASource",
    "from_peft_dir",
    "load_adapter",
    "LoraReplicaConfig",
    "LoraRoutingTable",
    "RendezvousHasher",
    "allocate",
]
