"""Host-side physical KV block allocator with content-addressed prefix cache.

The device cache is ``[num_blocks, block_size, kv_heads, head_dim]`` per layer
(ops/attention.py layout); this allocator owns which physical block holds
which sequence-hash, mirrored after the reference's block pool + reuse logic
(lib/llm/src/block_manager/pool/) at G1 scope. Block 0 is reserved as scratch
for padding writes and never allocated.

Emits stored/removed events (sequence-hash space) for the KV router feed.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

from ..tokens import SequenceHash


class OutOfBlocks(Exception):
    pass


class BlockAllocator:
    SCRATCH = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is scratch)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))  # pop() -> low ids first
        # committed content: seq_hash -> block id (active or cached)
        self._by_hash: Dict[SequenceHash, int] = {}
        self._refcount: Dict[int, int] = {}            # block id -> active refs
        self._hash_of: Dict[int, SequenceHash] = {}    # block id -> seq_hash
        # LRU of unpinned cached blocks (block ids), eviction order = insertion
        self._lru: OrderedDict[int, None] = OrderedDict()
        self.events_stored: List[List[SequenceHash]] = []
        self.events_removed: List[List[SequenceHash]] = []

    # -- introspection -------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._lru)

    @property
    def active_blocks(self) -> int:
        return sum(1 for rc in self._refcount.values() if rc > 0)

    @property
    def cached_blocks(self) -> int:
        return len(self._lru)

    # -- prefix cache --------------------------------------------------------
    def match_prefix(self, hashes: List[SequenceHash]) -> List[int]:
        """Longest cached prefix; returns (unpinned) block ids, no state change."""
        out: List[int] = []
        for h in hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            out.append(bid)
        return out

    def acquire_prefix(self, hashes: List[SequenceHash]) -> List[int]:
        """Pin the longest cached prefix for a request; returns its block ids."""
        ids = self.match_prefix(hashes)
        for bid in ids:
            self._pin(bid)
        return ids

    def _pin(self, bid: int) -> None:
        rc = self._refcount.get(bid, 0)
        if rc == 0:
            self._lru.pop(bid, None)
        self._refcount[bid] = rc + 1

    # -- allocation ----------------------------------------------------------
    def allocate(self, n: int) -> List[int]:
        """Grab n fresh blocks (evicting cached LRU if needed); pinned, no
        content hash yet (assign via commit)."""
        out: List[int] = []
        try:
            for _ in range(n):
                out.append(self._pop_free())
        except OutOfBlocks:
            for bid in out:  # roll back partial allocation
                self._free.append(bid)
            raise
        for bid in out:
            self._refcount[bid] = 1
        return out

    def _pop_free(self) -> int:
        if self._free:
            return self._free.pop()
        if self._lru:
            victim, _ = self._lru.popitem(last=False)  # evict oldest
            h = self._hash_of.pop(victim, None)
            if h is not None:
                del self._by_hash[h]
                self.events_removed.append([h])
            self._refcount.pop(victim, None)
            return victim
        raise OutOfBlocks(f"no free blocks ({self.num_blocks} total)")

    def can_allocate(self, n: int) -> bool:
        return self.free_blocks >= n

    # -- content commit / release -------------------------------------------
    def commit(self, bid: int, seq_hash: SequenceHash) -> None:
        """Blocks become content-addressed once sealed (full of tokens)."""
        existing = self._by_hash.get(seq_hash)
        if existing is not None and existing != bid:
            # duplicate content: keep both physical blocks but hash points at
            # the original; this block stays anonymous (freed on release)
            return
        self._by_hash[seq_hash] = bid
        self._hash_of[bid] = seq_hash
        self.events_stored.append([seq_hash])

    def release(self, block_ids: List[int]) -> None:
        """Unpin a request's blocks; sealed ones become evictable cache,
        anonymous ones return to the free list."""
        for bid in block_ids:
            rc = self._refcount.get(bid, 0)
            if rc > 1:
                self._refcount[bid] = rc - 1
                continue
            self._refcount.pop(bid, None)
            if bid in self._hash_of:
                self._lru[bid] = None
                self._lru.move_to_end(bid)
            else:
                self._free.append(bid)

    def drain_events(self) -> Tuple[List[List[SequenceHash]], List[List[SequenceHash]]]:
        stored, self.events_stored = self.events_stored, []
        removed, self.events_removed = self.events_removed, []
        return stored, removed

    def clear(self) -> None:
        """Drop the whole prefix cache (router gets a CLEARED event upstream)."""
        for bid in list(self._lru):
            h = self._hash_of.pop(bid, None)
            if h is not None:
                self._by_hash.pop(h, None)
            self._free.append(bid)
        self._lru.clear()
