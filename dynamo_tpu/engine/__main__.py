"""python -m dynamo_tpu.engine — a real TPU/JAX engine worker.

The TPU-native analog of `python -m dynamo.vllm` (components/src/dynamo/vllm/
main.py): brings up a TpuEngine (paged KV, continuous batching, TP-sharded
forward), registers the model card + endpoint, publishes KV events and load
metrics for the router.

Model selection:
  --model-path /path/to/hf_checkpoint   local HF llama/qwen checkpoint
  --preset tiny|qwen3-0.6b|llama3-8b|llama3-70b  random-init architecture
"""

import argparse
import asyncio
import os
import signal

from dynamo_tpu.engine.engine import TpuEngine, TpuEngineConfig
from dynamo_tpu.engine.weights import config_from_hf, load_params
from dynamo_tpu.kv_router import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm import ModelDeploymentCard, ModelRuntimeConfig, register_llm
from dynamo_tpu.models.llama import LlamaConfig
from dynamo_tpu.models.gemma import GemmaConfig
from dynamo_tpu.models.gptoss import GptOssConfig
from dynamo_tpu.models.mla import MlaConfig
from dynamo_tpu.models.moe import MoeConfig
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig, init_logging
from dynamo_tpu.runtime.component import new_instance_id
from dynamo_tpu.runtime.config import (
    ENV_KVBM_DISK_CACHE_GB,
    ENV_KVBM_DISK_PATH,
    ENV_KVBM_HOST_CACHE_GB,
    ENV_KVBM_REMOTE,
    ENV_MIGRATION_LIMIT,
    ENV_NAMESPACE,
    env_float,
    env_int,
    env_str,
)

PRESETS = {
    "tiny": lambda: LlamaConfig(),
    "qwen3-0.6b": LlamaConfig.qwen3_0_6b,
    "llama3-8b": LlamaConfig.llama3_8b,
    "llama3-70b": LlamaConfig.llama3_70b,
    "tiny-moe": MoeConfig.tiny_moe,
    "qwen3-30b-a3b": MoeConfig.qwen3_30b_a3b,
    "tiny-gptoss": GptOssConfig.tiny_gptoss,
    "gpt-oss-20b": GptOssConfig.gpt_oss_20b,
    "gpt-oss-120b": GptOssConfig.gpt_oss_120b,
    "tiny-gemma2": GemmaConfig.tiny_gemma2,
    "tiny-gemma3": GemmaConfig.tiny_gemma3,
    "gemma2-2b": GemmaConfig.gemma2_2b,
    "gemma3-4b": GemmaConfig.gemma3_4b,
    "tiny-mla": MlaConfig.tiny_mla,
    "tiny-mla-moe": MlaConfig.tiny_mla_moe,
    "deepseek-v2-lite": MlaConfig.deepseek_v2_lite,
    "deepseek-v3": MlaConfig.deepseek_v3,
    "tiny-vl": lambda: LlamaConfig(),  # language side; vision below
}

from dynamo_tpu.models.vision import VisionConfig

# vision towers paired with language presets (models/vision.py)
VISION_PRESETS = {
    "tiny-vl": lambda mcfg: VisionConfig.tiny(out_hidden_size=mcfg.hidden_size),
}


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.engine")
    p.add_argument("--model", default="tpu-model", help="served model name")
    p.add_argument("--model-path", default=None, help="local HF checkpoint dir")
    p.add_argument("--preset", default="tiny", choices=sorted(PRESETS))
    p.add_argument("--tokenizer", default=None, help="tokenizer path (default: model-path or byte)")
    p.add_argument("--tool-parser", default=None,
                   help="streaming tool-call dialect for this model's card "
                        "(parsers/tool_calls.py registry); default: harmony "
                        "for gpt-oss presets, else none")
    p.add_argument("--reasoning-parser", default=None,
                   help="reasoning-block parser for the card "
                        "(e.g. deepseek_r1, qwen3, gpt_oss; "
                        "parsers/reasoning.py registry); default: gpt_oss "
                        "for gpt-oss presets, else none")
    p.add_argument("--namespace", default=env_str(ENV_NAMESPACE, "dynamo"))
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--store", default=None)
    p.add_argument("--store-path", default=None)
    p.add_argument("--event-plane", default=None)
    p.add_argument("--status-port", type=int, default=-1,
                   help="system status server port (/health /live /metrics "
                   "/metadata); 0 = ephemeral, -1 = disabled")
    p.add_argument("--graceful-timeout", type=float, default=10.0,
                   help="seconds to wait for in-flight requests on shutdown")
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "axon"],
        help="force the JAX backend (the axon TPU plugin pins itself even "
        "when JAX_PLATFORMS=cpu; this applies jax.config.update early so "
        "CPU smoke runs work on TPU hosts)",
    )
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel ranks served by this worker: each rank "
                   "gets its own engine + KV pool on its own tp-sized device "
                   "group; the KV router targets (worker, dp_rank)")
    p.add_argument("--num-blocks", type=int, default=2048)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--kv-dtype", default="auto",
                   choices=("auto", "model", "int8"),
                   help="paged-KV storage precision (docs/operations.md "
                        "'KV precision'): int8 = quantized cache w/ "
                        "per-block scales, ~0.51x bf16 KV bytes; auto "
                        "defers to DTPU_KV_DTYPE (default: model dtype)")
    p.add_argument("--mixed", default="auto", choices=("auto", "on", "off"),
                   help="mixed continuous batching (docs/operations.md 5c): "
                        "a prefill chunk fuses with the resident decode "
                        "batch through the unified ragged kernel; auto "
                        "defers to DTPU_MIXED (default on, auto-gated off "
                        "for pp/sp/spec/vision/LoRA/multihost)")
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--max-context", type=int, default=2048,
                   help="may exceed the largest prefill bucket: long prompts "
                   "prefill in bounded chunks")
    p.add_argument("--prefill-chunk", type=int, default=2048,
                   help="largest single prefill dispatch (= largest bucket)")
    p.add_argument("--sp", type=int, default=1,
                   help="context-parallel ring attention width for chunk "
                   "prefill (sequence sharded over the sp mesh axis)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages for serving: layers + "
                   "paged KV shard over a pp mesh axis, activations ride a "
                   "shard_map wavefront (parallel/pp_serving.py)")
    p.add_argument("--migration-limit", type=int,
                   default=env_int(ENV_MIGRATION_LIMIT, 0))
    p.add_argument("--kvbm-host-gb", type=float,
                   default=env_float(ENV_KVBM_HOST_CACHE_GB, 0.0),
                   help="host DRAM KV tier size (G2); 0 disables kvbm")
    p.add_argument("--kvbm-disk-gb", type=float,
                   default=env_float(ENV_KVBM_DISK_CACHE_GB, 0.0),
                   help="disk KV tier size (G3)")
    p.add_argument("--kvbm-disk-path",
                   default=env_str(ENV_KVBM_DISK_PATH, "/tmp/dtpu_kvbm"))
    p.add_argument("--kvbm-remote",
                   default=(env_str(ENV_KVBM_REMOTE, "") or None),
                   metavar="HOST:PORT",
                   help="G4 fleet-shared block store "
                        "(python -m dynamo_tpu.kvbm)")
    p.add_argument("--lora-max-adapters", type=int, default=0,
                   help="static multi-LoRA slots; enables the load_lora/"
                        "unload_lora/list_loras endpoints (reference "
                        "components/src/dynamo/vllm/main.py:712)")
    p.add_argument("--lora-rank", type=int, default=16)
    p.add_argument("--no-warm-cache", action="store_true",
                   help="disable the host weight cache (engine/warm.py)")
    p.add_argument("--decode-steps", type=int, default=None,
                   help="decode iterations per compiled horizon; default "
                        "auto-tunes from the measured device RTT (multihost "
                        "pins 32 — per-process autotune would desync the "
                        "replayed programs)")
    p.add_argument("--decode-pipeline", type=int, default=None,
                   help="in-flight decode horizons; default auto-tunes with "
                        "--decode-steps (multihost pins 2)")
    p.add_argument("--weight-service", default=None, metavar="SOCK",
                   help="unix socket of a weight owner process "
                        "(engine/weight_service.py; reference "
                        "lib/gpu_memory_service): import weights from host "
                        "shared memory instead of parsing the checkpoint; "
                        "also honors $DTPU_WEIGHT_SERVICE")
    p.add_argument("--logits-processors", default=None,
                   help="named example processors to register, e.g. "
                        "'ban=5,7,9;temperature=0.7;norepeat=2.0' — requests "
                        "opt in via the logits_processors field "
                        "(dynamo_tpu/logits_processing)")
    p.add_argument("--spec-draft", default=None, choices=sorted(PRESETS),
                   help="enable speculative decoding with this draft "
                        "architecture (random-init unless --spec-draft-path; "
                        "docs/speculative_decoding.md). Greedy requests are "
                        "served spec; sampled ones fall back per dispatch")
    p.add_argument("--spec-draft-path", default=None,
                   help="local HF checkpoint (or hub ref) for the draft "
                        "model; implies --spec-draft semantics with the "
                        "checkpoint's architecture")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens per speculative round (clamped to "
                        "decode-steps)")
    p.add_argument("--guided-max-states", type=int, default=1024,
                   help="guided decoding automaton cap (dynamo_tpu/guided): "
                        "grammars compile to per-slot device tables "
                        "[states x classes]; 0 disables guided decoding "
                        "entirely (no mask ops in the decode programs)")
    p.add_argument("--guided-max-classes", type=int, default=320,
                   help="guided decoding token-class cap (see above)")
    p.add_argument("--eplb-redundant-experts", type=int, default=0,
                   help="EPLB (models/eplb.py): add N redundant physical "
                        "expert slots to a MoE model and spread hot "
                        "experts' tokens across replicas; rebalance at "
                        "runtime from measured loads. MoE presets/"
                        "checkpoints only; (E+N) must divide over tp")
    p.add_argument(
        "--disagg",
        choices=["none", "prefill", "decode"],
        default="none",
        help="prefill: join the prefill pool + serve kv_fetch; decode: serve "
        "decode with remote-KV import (also serves kv_fetch for peers)",
    )
    p.add_argument(
        "--multihost", default=None,
        metavar="COORD:PORT,NPROCS,PROC_ID[,CONTROL:PORT]",
        help="multi-process serving over one jax.distributed mesh: process 0 "
        "owns the endpoint + scheduler and broadcasts every dispatch; other "
        "processes replay them (runtime/multihost.py). tp*sp must equal the "
        "GLOBAL device count. Reference analog: one logical worker per TP "
        "group with non-leader ranks idling in the engine step loop "
        "(components/src/dynamo/vllm/main.py:67)",
    )
    return p.parse_args()


def _load_guided_vocab(engine_cfg, tokenizer_ref):
    """(vocab byte forms, eos_id) when the config enables guided decoding,
    else None. Shared by leader AND followers of a multihost group (the
    guided programs are traced on every process; a vocab drift would desync
    the replayed dispatches). A tokenizer without an EOS id cannot terminate
    grammars — guided is disabled rather than refusing to serve."""
    if engine_cfg.guided_max_states <= 0:
        return None
    from dynamo_tpu.guided import vocab_bytes_from_tokenizer
    from dynamo_tpu.llm.tokenizer import load_tokenizer

    try:
        return vocab_bytes_from_tokenizer(load_tokenizer(tokenizer_ref))
    except ValueError as e:
        print(f"guided decoding disabled: {e}", flush=True)
        engine_cfg.guided_max_states = 0
        return None


def _load_draft(args):
    """(draft_cfg, draft_params) for --spec-draft/--spec-draft-path, or
    (None, None). Checkpoint drafts ride the same warm-cache path as the
    main model."""
    if getattr(args, "spec_draft_path", None):
        from dynamo_tpu.llm.hub import resolve_model_path

        path = resolve_model_path(args.spec_draft_path)
        dcfg = config_from_hf(path)
        if args.no_warm_cache:
            return dcfg, load_params(path, dcfg)
        from dynamo_tpu.engine.warm import load_params_warm

        return dcfg, load_params_warm(path, dcfg)
    if getattr(args, "spec_draft", None):
        return PRESETS[args.spec_draft](), None
    return None, None


def make_engine_config(args, mcfg, vcfg=None, logits_procs=(), spec_draft=None):
    """TpuEngineConfig from CLI args — ONE code path for every process of a
    multihost group (leader/follower config drift would desync the replayed
    XLA programs)."""
    bs = args.block_size

    def rnd(n):  # round up to a block multiple
        return ((n + bs - 1) // bs) * bs

    ctx = rnd(args.max_context)
    # buckets bound the CHUNK size, not the context: long prompts prefill in
    # chunks of the largest bucket, so a 16k+ context never compiles a 16k-
    # wide prefill program
    chunk_cap = min(ctx, rnd(args.prefill_chunk))
    buckets = tuple(
        rnd(b) for b in (64, 128, 256, 512, 1024, 2048, 4096, 8192)
        if rnd(b) < chunk_cap
    ) + (chunk_cap,)
    args.max_context = ctx
    # decode schedule: per-process RTT autotune is NOT multihost-safe (the
    # horizon length is baked into the compiled program; leader/follower
    # resolving different steps from noisy RTT medians would desync the
    # replayed dispatches) — multihost pins the measured tunneled-TPU
    # defaults unless the flags say otherwise
    decode_steps = getattr(args, "decode_steps", None)
    decode_pipeline = getattr(args, "decode_pipeline", None)
    if getattr(args, "multihost", None):
        decode_steps = decode_steps if decode_steps is not None else 32
        decode_pipeline = decode_pipeline if decode_pipeline is not None else 2
    return TpuEngineConfig(
        decode_steps=decode_steps,
        decode_pipeline=decode_pipeline,
        model=mcfg,
        num_blocks=args.num_blocks,
        block_size=args.block_size,
        kv_dtype=getattr(args, "kv_dtype", "auto"),
        mixed_admission=(
            None if getattr(args, "mixed", "auto") == "auto"
            else getattr(args, "mixed") == "on"
        ),
        max_batch_size=args.max_batch_size,
        max_context=ctx,
        tp=args.tp,
        sp=args.sp,
        pp=getattr(args, "pp", 1),
        prefill_buckets=buckets,
        lora_max_adapters=args.lora_max_adapters,
        lora_rank=args.lora_rank,
        logits_processors=logits_procs,
        vision=vcfg,
        spec_draft=spec_draft,
        spec_k=getattr(args, "spec_k", 4),
        # the pp sampling epilogues don't carry the mask ops — force guided
        # off rather than fail construction on default flags
        guided_max_states=(
            0 if getattr(args, "pp", 1) > 1
            else getattr(args, "guided_max_states", 0)
        ),
        guided_max_classes=getattr(args, "guided_max_classes", 320),
    )


def _build_logits_procs(args):
    """Parse --logits-processors into static (name, fn) pairs. Shared by the
    leader AND followers of a multihost group: the processors are traced into
    the XLA programs, so a config drift would desync the replayed programs."""
    if not args.logits_processors:
        return ()
    from dynamo_tpu.logits_processing import (
        ban_tokens_processor,
        repetition_window_processor,
        temperature_processor,
    )

    built = []
    for spec in args.logits_processors.split(";"):
        pname, _, val = spec.strip().partition("=")
        if pname == "ban":
            built.append(("ban", ban_tokens_processor(
                [int(t) for t in val.split(",") if t]
            )))
        elif pname == "temperature":
            built.append(("temperature", temperature_processor(float(val))))
        elif pname == "norepeat":
            built.append(("norepeat", repetition_window_processor(float(val))))
        else:
            raise SystemExit(f"unknown logits processor {pname!r}")
    return tuple(built)


def _load_model(args):
    """(mcfg, params, tokenizer_ref) from CLI args; shared by every process
    of a multihost group (identical host weights on each process are what
    make the collective device_put shards consistent)."""
    params = None
    if args.model_path:
        # --model-path accepts a local dir OR a hub reference ("org/name"
        # resolved through the HF cache / optional download — llm/hub.py,
        # reference lib/llm/src/hub.rs)
        from dynamo_tpu.llm.hub import resolve_model_path

        path = resolve_model_path(args.model_path)
        mcfg = config_from_hf(path)
        ws_sock = getattr(args, "weight_service", None) or os.environ.get(
            "DTPU_WEIGHT_SERVICE"
        )
        if ws_sock:
            # out-of-process weight import (engine/weight_service.py,
            # gpu_memory_service analog): zero-copy mmap from the owner's
            # tmpfs; the client connection is the lease — parked on args so
            # it lives as long as the process
            from dynamo_tpu.engine.weight_service import load_params_served

            params, args._weight_lease = load_params_served(
                path, mcfg, ws_sock,
                warm_fallback=not args.no_warm_cache,
            )
        elif args.no_warm_cache:
            params = load_params(path, mcfg)
        else:
            # warm restore (engine/warm.py): restarted workers skip the
            # checkpoint parse (chrek/CRIU analog, SURVEY §2.4)
            from dynamo_tpu.engine.warm import load_params_warm

            params = load_params_warm(path, mcfg)
        tokenizer_ref = args.tokenizer or path
    else:
        mcfg = PRESETS[args.preset]()
        tokenizer_ref = args.tokenizer or "byte"
    n_red = getattr(args, "eplb_redundant_experts", 0)
    if n_red > 0:
        import dataclasses as _dc

        if getattr(mcfg, "num_experts", 0) <= 0 or not hasattr(
            mcfg, "redundant_experts"
        ):
            raise SystemExit(
                "--eplb-redundant-experts needs a MoeConfig-family model"
            )
        mcfg = _dc.replace(mcfg, redundant_experts=n_red)
    return mcfg, params, tokenizer_ref


def _multihost_mesh(args, mh, rank: int = 0):
    """Rank ``rank``'s mesh, built identically on every process of the group.

    dp ranks take a STRIDED slice of the global device list
    (``devices[rank::dp]``): with process-major global ordering every rank's
    mesh spans every process, which is required — a process can only build /
    replay an engine whose arrays have addressable shards on it. (Contiguous
    slices would make each rank process-local; that layout is just N
    independent workers and needs no multihost group.)"""
    import jax

    from dynamo_tpu.parallel.mesh import make_mesh

    n = jax.device_count()
    group = (args.pp * args.tp) if args.pp > 1 else (args.tp * args.sp)
    if args.dp * group != n:
        raise SystemExit(
            f"--multihost needs dp*(pp*)tp*sp == global device count: "
            f"dp={args.dp} pp={args.pp} tp={args.tp} sp={args.sp} vs {n} "
            f"devices over {mh.num_processes} processes"
        )
    if args.dp > 1 and jax.local_device_count() % args.dp:
        raise SystemExit(
            f"--multihost dp={args.dp} needs local device count "
            f"({jax.local_device_count()}) divisible by dp so every rank "
            f"spans every process"
        )
    devs = jax.devices()[rank :: args.dp]
    if args.pp > 1:
        from dynamo_tpu.parallel.pp_serving import make_pp_mesh

        return make_pp_mesh(pp=args.pp, tp=args.tp, devices=devs)
    return make_mesh(tp=args.tp, sp=args.sp, devices=devs)


def _mh_ns(args, rank: int) -> str:
    return f"dp{rank}" if args.dp > 1 else ""


async def main() -> None:
    args = parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    init_logging()
    mh = None
    if args.multihost:
        from dynamo_tpu.runtime.multihost import MultihostContext, MultihostSpec

        mh = MultihostContext(MultihostSpec.parse(args.multihost))
        mh.initialize_jax()  # must precede any device use
        mh.start_control()

    if mh is not None and not mh.is_leader:
        # follower: no endpoint, no discovery — join the mesh, build the
        # SAME engines (params + caches are collective device_puts), replay
        # the leader's dispatches until it stops
        mcfg, params, follower_tok = _load_model(args)
        draft_cfg, draft_params = _load_draft(args)
        engine_cfg = make_engine_config(
            args, mcfg, logits_procs=_build_logits_procs(args),
            spec_draft=draft_cfg,
        )
        follower_gv = _load_guided_vocab(engine_cfg, follower_tok)
        engines = [
            TpuEngine(
                engine_cfg, params=params, draft_params=draft_params,
                guided_vocab=follower_gv,
                mesh=_multihost_mesh(args, mh, r),
                multihost=mh, mh_ns=_mh_ns(args, r),
            )
            for r in range(args.dp)
        ]
        print(f"TPU_ENGINE_FOLLOWER_READY proc={mh.spec.process_id}", flush=True)
        loop = asyncio.get_running_loop()
        try:
            # ONE replay loop serves every rank's table (namespaced ops)
            await loop.run_in_executor(None, mh.router.follow)
        except Exception:
            import traceback

            traceback.print_exc()
            mh.close()
            # skip the distributed-shutdown barrier: the leader is still
            # serving and would never join it — exit hard so a supervisor
            # can restart the group instead of wedging on a half-dead mesh
            import os as _os

            _os._exit(1)
        mh.close()
        mh.shutdown_jax()
        return

    cfg = RuntimeConfig.from_env(
        store=args.store, store_path=args.store_path, event_plane=args.event_plane
    )
    runtime = await DistributedRuntime(cfg).start()

    mcfg, params, tokenizer_ref = _load_model(args)
    vcfg = None
    if args.preset in VISION_PRESETS and not args.model_path:
        vcfg = VISION_PRESETS[args.preset](mcfg)

    component = args.component
    model_type = ["chat", "completions", "embedding"]
    if args.disagg == "prefill":
        component = (
            args.component + "_prefill" if args.component == "backend" else args.component
        )
        model_type = ["prefill"]

    instance_id = new_instance_id()
    kvbm = None
    if args.kvbm_host_gb > 0 or args.kvbm_disk_gb > 0 or args.kvbm_remote:
        from dynamo_tpu.kvbm.layout import kv_bytes_per_token
        from dynamo_tpu.kvbm.pool import KvbmTiers
        from dynamo_tpu.ops.quant import resolve_kv_dtype

        # size tiers in STORED bytes per block (model dtype, or the int8
        # codec buffer) — a hardcoded 4 bytes/element would under-use the
        # configured budget 2-4x for bf16/int8 caches. kv_bytes_per_token
        # is the one byte-accounting source (kvbm/layout).
        kvd = resolve_kv_dtype(getattr(args, "kv_dtype", "auto"))
        block_nbytes = int(
            kv_bytes_per_token(mcfg, args.block_size, kvd) * args.block_size
        )
        remote = None
        if args.kvbm_remote:
            from dynamo_tpu.kvbm.remote import RemoteBlockPool

            remote = RemoteBlockPool(args.kvbm_remote)
        kvbm = KvbmTiers(
            block_nbytes,
            host_capacity_bytes=int(args.kvbm_host_gb * (1 << 30)),
            disk_capacity_bytes=int(args.kvbm_disk_gb * (1 << 30)),
            disk_path=args.kvbm_disk_path,
            remote=remote,
        )
    draft_cfg, draft_params = _load_draft(args)
    engine_cfg = make_engine_config(
        args, mcfg, vcfg=vcfg, logits_procs=_build_logits_procs(args),
        spec_draft=draft_cfg,
    )
    guided_vocab = _load_guided_vocab(engine_cfg, tokenizer_ref)

    import jax as _jax

    from dynamo_tpu.parallel.mesh import make_mesh

    def rank_mesh(rank: int):
        """Each dp_rank serves from its own device group when the host has
        enough chips; otherwise ranks share (CPU smoke / 1 chip)."""
        devs = _jax.devices()
        if args.pp > 1:
            from dynamo_tpu.parallel.pp_serving import make_pp_mesh

            group = args.pp * args.tp
            if len(devs) < group:
                raise SystemExit(
                    f"--pp {args.pp} --tp {args.tp} needs {group} devices; "
                    f"{len(devs)} available (pp stages cannot share a chip)"
                )
            if len(devs) >= args.dp * group:
                lo = rank * group
            else:
                lo = 0
                if rank == 0 and args.dp > 1 and _jax.default_backend() != "cpu":
                    print(
                        f"WARNING: {len(devs)} device(s) < dp*pp*tp="
                        f"{args.dp * group}; all {args.dp} ranks share the "
                        f"same chips (HBM use scales with dp).",
                        flush=True,
                    )
            return make_pp_mesh(
                pp=args.pp, tp=args.tp, devices=devs[lo : lo + group]
            )
        group = args.tp * args.sp
        lo = rank * group
        if len(devs) >= args.dp * group:
            return make_mesh(tp=args.tp, sp=args.sp, devices=devs[lo : lo + group])
        if rank == 0 and args.dp > 1 and _jax.default_backend() != "cpu":
            # sharing chips means every rank allocates a FULL KV cache +
            # param copy on the same HBM — fine for smoke runs, an OOM
            # hazard on real hardware
            print(
                f"WARNING: {len(devs)} device(s) < dp*tp={args.dp * args.tp}; "
                f"all {args.dp} ranks share the same chips (HBM use scales "
                f"with dp). Provision dp*tp chips for real dp serving.",
                flush=True,
            )
        n = min(len(devs), args.tp * args.sp)
        sp = args.sp if n >= args.tp * args.sp else 1
        return make_mesh(tp=args.tp, sp=sp, devices=devs[: args.tp * sp])

    engines = []
    for r in range(args.dp):
        kv_pub = KvEventPublisher(
            runtime.event_plane, args.namespace, component,
            worker_id=instance_id, dp_rank=r, block_size=args.block_size,
        )
        m_pub = WorkerMetricsPublisher(
            runtime.event_plane, args.namespace, component,
            worker_id=instance_id, dp_rank=r,
        )
        engines.append(
            TpuEngine(
                engine_cfg,
                params=params,
                draft_params=draft_params,
                guided_vocab=guided_vocab,
                mesh=(_multihost_mesh(args, mh, r) if mh is not None
                      else rank_mesh(r)),
                kv_publisher=kv_pub,
                metrics_publisher=m_pub,
                kvbm=kvbm if r == 0 else None,  # host tiers are rank-0 only
                multihost=mh,
                mh_ns=_mh_ns(args, r),
            )
        )
    if args.dp > 1:
        from dynamo_tpu.engine.dp import DpEngineGroup

        engine = DpEngineGroup(engines)
    else:
        engine = engines[0]
    # step telemetry (engine/telemetry.py): every rank's loop feeds StepStats
    # into the runtime registry under the component hierarchy labels, so
    # /metrics exposes step-duration/occupancy/queue-depth per (worker, rank)
    from dynamo_tpu.engine.telemetry import EngineTelemetry

    tele_scope = runtime.metrics.child(
        dtpu_namespace=args.namespace, dtpu_component=component,
        dtpu_endpoint=args.endpoint,
    )
    # degradation detectors (runtime/health.py): the step hook below feeds
    # measured-vs-modeled step time into cost_model_drift; events land on
    # the flight recorder, the metrics registry, and the event plane
    from dynamo_tpu.runtime.health import get_health_monitor

    health_monitor = get_health_monitor()
    health_monitor.bind_metrics(tele_scope)

    def _predicted_step_s(s) -> float:
        """ops/costs.py roofline floor for the step the hook just saw.
        The exact per-row mix is gone by hook time, so rows are the
        occupancy-mean context — fine for drift detection, which trips on
        the measured/predicted RATIO moving, not its absolute level
        (calibrate DTPU_HEALTH_DRIFT_RATIO per platform)."""
        from dynamo_tpu.ops.costs import predict_step_seconds

        occ = max(s.batch_occupancy, 1)
        mean_len = max(s.kv_active_blocks * args.block_size // occ, 1)
        q = max(s.tokens // occ, 1) if s.phase != "decode" else 1
        return predict_step_seconds(
            [(q, mean_len)] * occ,
            block_size=args.block_size,
            kv_heads=getattr(mcfg, "num_kv_heads", 8),
            num_heads=getattr(mcfg, "num_heads", 32),
            head_dim=getattr(mcfg, "head_dim", 128),
            layers=getattr(mcfg, "num_layers", 32),
            # sustained HBM stream prior (v5e-class, ~0.8 TB/s); only the
            # ratio's drift matters, not the absolute calibration
            hbm_bytes_s=8.0e11,
            dispatch_s=5e-3,
        )

    telemetries = []
    for r, e in enumerate(engines):
        tele = EngineTelemetry(tele_scope.child(dp_rank=str(r)))
        telemetries.append(tele)

        def _hook(s, _tele=tele, _r=r):
            _tele.on_step(s)
            try:
                health_monitor.observe_step(
                    f"worker/{instance_id:016x}/dp{_r}",
                    s.duration_s, _predicted_step_s(s), phase=s.phase,
                )
            except Exception:
                pass  # the detector must never take the step loop down

        e.stats_hook = _hook
    # per-wire KV transfer bandwidth EWMA onto /metrics (the decode side of
    # a disagg pair observes pulls here; routing elsewhere reads the gauge)
    from dynamo_tpu.runtime.bandwidth import get_bandwidth_estimator

    get_bandwidth_estimator().attach_metrics(tele_scope)
    # worker-side SLO ledger (runtime/slo.py): the engine feeds the global
    # accountant from milestone timestamps; binding it here puts goodput +
    # attainment/burn gauges on this worker's /metrics (and /debug/slo on
    # the status server reads the same ledger)
    from dynamo_tpu.runtime.slo import get_slo_accountant

    get_slo_accountant().bind_metrics(tele_scope)
    if mh is not None:
        # follower death is unrecoverable for the group (its mesh shards are
        # gone): mark every engine unhealthy — the watchdog deregisters and
        # exits us for a supervisor restart — and slam the group closed so a
        # wedged dispatch raises instead of hanging. In-flight client streams
        # drop with the process; the frontend's Migration replays them on
        # another worker (llm/migration.py).
        def _on_follower_death() -> None:
            print("MULTIHOST_FOLLOWER_LOST", flush=True)
            for e in engines:
                e.healthy = False
            mh.router.close(timeout_s=2.0)

        mh.watch_followers(_on_follower_death)
    transfer_md = {}
    if args.disagg in ("prefill", "decode"):
        transfer_engine = engines[0]
        addr = await transfer_engine.serve_transfer(host=cfg.host_ip)
        print(f"KV_TRANSFER at {addr}", flush=True)
        # advertise the fetch address at registration: streamed disagg
        # dispatches the decode hop before prefill finishes, so the
        # frontend needs it at routing time (register_llm also picks it up
        # from the engine; setting it here covers dp groups whose facade
        # object is not engines[0])
        transfer_md = {
            "transfer_address": addr,
            "kv_wire": os.environ.get("DTPU_KV_WIRE", "inline"),
        }

    kv_directory = None
    if kvbm is not None:
        from dynamo_tpu.kvbm.directory import GlobalKvDirectory, directory_enabled

        if directory_enabled():
            # fleet-wide KV reuse (kvbm/directory.py): rank 0 owns the host
            # tiers, so it advertises sealed blocks under a store lease and
            # serves peer pulls over the kv_fetch transfer plane — start
            # that plane even in aggregated mode, where --disagg did not.
            gkv_addr = transfer_md.get("transfer_address")
            if gkv_addr is None:
                gkv_addr = await engines[0].serve_transfer(host=cfg.host_ip)
                print(f"KV_TRANSFER at {gkv_addr}", flush=True)
                transfer_md = {
                    "transfer_address": gkv_addr,
                    "kv_wire": os.environ.get("DTPU_KV_WIRE", "inline"),
                }
            kv_directory = GlobalKvDirectory(
                runtime.store, f"worker/{instance_id}", address=gkv_addr,
                metrics=runtime.metrics,
            )
            await kv_directory.start()
            engines[0].kv_directory = kv_directory

    # parser names fail FAST at worker startup (the frontend's _safe_parser
    # degrades unknown names to pass-through with only a warning); gpt-oss
    # presets default to the harmony dialect + its reasoning channels
    is_oss = isinstance(mcfg, GptOssConfig)
    tool_parser = args.tool_parser if args.tool_parser is not None else (
        "harmony" if is_oss else None
    )
    reasoning_parser = (
        args.reasoning_parser if args.reasoning_parser is not None
        else ("gpt_oss" if is_oss else None)
    )
    from dynamo_tpu.parsers import get_reasoning_parser, get_tool_parser

    get_tool_parser(tool_parser)
    get_reasoning_parser(reasoning_parser)

    card = ModelDeploymentCard(
        name=args.model,
        namespace=args.namespace,
        component=component,
        endpoint=args.endpoint,
        model_type=model_type,
        tokenizer=tokenizer_ref,
        context_length=args.max_context,
        kv_block_size=args.block_size,
        migration_limit=args.migration_limit,
        image_tokens=(vcfg.num_patches if vcfg is not None else 0),
        image_size=(vcfg.image_size if vcfg is not None else 0),
        image_token_id=engine_cfg.image_token_id,
        tool_parser=tool_parser,
        reasoning_parser=reasoning_parser,
        runtime_config=ModelRuntimeConfig(
            total_kv_blocks=args.num_blocks,
            data_parallel_size=args.dp,
            kv_block_size=args.block_size,
            max_batch_size=args.max_batch_size,
            tensor_parallel_size=args.tp,
            max_context_len=args.max_context,
        ),
    )
    served = await register_llm(
        runtime, engine, card, instance_id=instance_id,
        metadata=transfer_md or None,
    )

    # LoRA management endpoints (load/unload/list), served beside generate
    lora_served = []
    if args.lora_max_adapters > 0:
        from dynamo_tpu.lora import LoRACache, LocalLoRASource, load_adapter

        lora_cache = LoRACache()
        lora_source = LocalLoRASource()
        # every dp rank owns its own engine (and mesh), so each gets its own
        # adapter table: load/unload fan out to all of them
        lora_engines = [e for e in engines if e.lora is not None]

        async def handle_load(request, context):
            name, uri = request["name"], request["uri"]
            loop_ = asyncio.get_event_loop()

            def work():
                path = lora_source.fetch(uri, lora_cache)
                weights, alpha = load_adapter(path)
                return [e.lora.load(name, weights, alpha) for e in lora_engines]

            try:
                slots = await loop_.run_in_executor(None, work)
                yield {"ok": True, "name": name, "slot": slots[0]}
            except Exception as e:
                yield {"ok": False, "error": str(e)}

        async def handle_unload(request, context):
            oks = [e.lora.unload(request["name"]) for e in lora_engines]
            yield {"ok": all(oks)}

        async def handle_list(request, context):
            yield {"adapters": lora_engines[0].lora.list_adapters()}

        comp = runtime.namespace(args.namespace).component(component)
        for ep_name, handler in (
            ("load_lora", handle_load),
            ("unload_lora", handle_unload),
            ("list_loras", handle_list),
        ):
            lora_served.append(await comp.endpoint(ep_name).serve(handler))

    # runtime cache reset (reference http/clear_kv_blocks.rs); dp>1 fans to
    # every rank's engine
    from dynamo_tpu.llm.serve import serve_clear_endpoint

    clear_served = await serve_clear_endpoint(
        runtime, args.namespace, component, engines, served.instance_id
    )
    eplb_served = None
    if getattr(mcfg, "redundant_experts", 0) > 0:
        from dynamo_tpu.llm.serve import serve_eplb_endpoint

        eplb_served = await serve_eplb_endpoint(
            runtime, args.namespace, component, engines, served.instance_id
        )

    # health: engine watchdog + endpoint canary + status side-port
    # (reference: engine_monitor.py, health_check.rs, system_status_server.rs)
    from dynamo_tpu.engine.monitor import EngineWatchdog
    from dynamo_tpu.runtime.health import EndpointCanary, HealthState, StatusServer

    stop = asyncio.Event()
    health = HealthState()

    # planned reclaims (docs/operations.md §13): restore warm state from a
    # prior drain's G3 checkpoint, then stand up the drain coordinator so a
    # POST /drain (or supervisor call) runs the evacuate-and-checkpoint
    # pipeline before the kill
    from dynamo_tpu.engine.checkpoint import restore_engine, weights_ref_for
    from dynamo_tpu.engine.drain import DrainCoordinator
    from dynamo_tpu.runtime import metrics as M_
    from dynamo_tpu.runtime.config import ENV_CKPT_DIR

    ckpt_dir = env_str(ENV_CKPT_DIR, "") or None
    restore_mode = None
    if ckpt_dir:
        restored = await restore_engine(engines[0], ckpt_dir)
        restore_mode = restored["mode"]
        tele_scope.gauge(
            M_.CHECKPOINT_RESTORE_MODE,
            "1 for the restore mode this worker booted with",
            extra_labels=("mode",),
        ).set(1, mode=restored["mode"])
        print(
            f"CHECKPOINT_RESTORE mode={restored['mode']} "
            f"blocks={restored['blocks']}", flush=True,
        )
    drain_coordinator = DrainCoordinator(
        engine, served,
        ckpt_dir=ckpt_dir,
        weights_ref=weights_ref_for(args.model_path or args.preset, mcfg),
        metrics_scope=tele_scope,
        on_drained=stop.set,
    )

    async def on_down() -> None:
        stop.set()  # watchdog already deregistered; exit so a supervisor restarts

    watchdog = EngineWatchdog(engine, [served], state=health, on_down=on_down).start()
    canary = EndpointCanary(
        {f"{card.component}/{card.endpoint}": served.address}, state=health
    ).start()
    status_server = None
    if args.status_port >= 0:
        g_running = runtime.metrics.gauge("dtpu_engine_running_seqs", "active sequences")
        g_waiting = runtime.metrics.gauge("dtpu_engine_waiting_seqs", "queued sequences")
        g_free = runtime.metrics.gauge("dtpu_engine_free_blocks", "free KV blocks")
        g_cached = runtime.metrics.gauge("dtpu_engine_cached_blocks", "prefix-cached KV blocks")

        def refresh_gauges() -> None:
            snap = engine.snapshot()
            ranks = snap["ranks"] if "ranks" in snap else [snap]
            g_running.set(sum(r["running"] for r in ranks))
            g_waiting.set(sum(r["waiting"] for r in ranks))
            g_free.set(sum(r["free_blocks"] for r in ranks))
            g_cached.set(sum(r["cached_blocks"] for r in ranks))
            # rolling attainment/burn gauges follow the scrape clock
            get_slo_accountant().export_metrics()

        def worker_snapshot() -> dict:
            """The ``/debug/worker`` document — everything the frontend's
            ``/debug/fleet`` fan-out (llm/fleet.py) merges from this worker
            in one call: engine + step telemetry, the SLO ledger, the
            attribution windows, KV occupancy, drain/restore state, the
            global-KV directory stats, wire bandwidth, health events."""
            from dynamo_tpu.runtime.attribution import get_attribution
            from dynamo_tpu.runtime.slo import debug_slo_payload

            snap = engine.snapshot()
            ranks = snap["ranks"] if "ranks" in snap else [snap]
            doc = {
                "instance_id": f"{instance_id:016x}",
                "model": args.model,
                "tp": args.tp,
                "dp": args.dp,
                "engine": snap,
                "telemetry": [t.snapshot() for t in telemetries],
                "slo": debug_slo_payload(get_slo_accountant()),
                "attribution": get_attribution().snapshot(),
                "bandwidth": get_bandwidth_estimator().snapshot(),
                "health": health_monitor.snapshot(),
                "drain": {"draining": drain_coordinator.ledger.draining},
                "kv": {
                    "active_blocks": sum(
                        r.get("active_blocks", 0) for r in ranks
                    ),
                    "free_blocks": sum(r.get("free_blocks", 0) for r in ranks),
                    "total_blocks": args.num_blocks * args.dp,
                    "cached_blocks": sum(
                        r.get("cached_blocks", 0) for r in ranks
                    ),
                },
            }
            if restore_mode is not None:
                doc["restore_mode"] = restore_mode
            if kv_directory is not None:
                doc["global_kv"] = {
                    "published": kv_directory.published_count,
                    "inflight_fetches": kv_directory.inflight_fetches(),
                    "dedupe_skipped": kv_directory.dedupe_skipped,
                }
            return doc

        status_server = StatusServer(
            health,
            metrics_scope=runtime.metrics,
            pre_expose=refresh_gauges,
            metadata_fn=lambda: {
                "model": args.model,
                "instance_id": f"{instance_id:016x}",
                "tp": args.tp,
                "engine": engine.snapshot(),
                "canary_rtt_s": canary.last_rtt,
            },
            port=args.status_port,
            loras_fn=(
                (lambda: engines[0].lora.list_adapters())
                if engines[0].lora is not None else None
            ),
            drain_fn=drain_coordinator.begin,
            worker_snapshot_fn=worker_snapshot,
        )
        await status_server.start()
        # advertise the side port on the discovery record so the frontend's
        # /debug/fleet fan-out can find this worker's /debug/worker
        await served.update_metadata({
            "status_address": f"{cfg.host_ip}:{status_server.port}",
        })

    # health events onto the event plane: planners/supervisors subscribe to
    # dtpu.health.* without scraping; the subscription handle is closed on
    # shutdown (RESOURCE-LEAK health-subscription)
    import json as _json

    from dynamo_tpu.runtime.tasks import spawn_bg as _spawn_bg

    _main_loop = asyncio.get_running_loop()

    def _publish_health(ev) -> None:
        payload = _json.dumps(ev.to_dict()).encode()
        coro = runtime.event_plane.publish(
            f"dtpu.health.{ev.detector}", payload
        )
        try:
            _main_loop.call_soon_threadsafe(_spawn_bg, coro)
        except RuntimeError:
            coro.close()  # loop already closed during shutdown

    health_sub = health_monitor.subscribe(_publish_health)
    print(f"TPU_ENGINE_READY {args.model} tp={args.tp}", flush=True)

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    # graceful drain: deregister first (discovery stops routing here), then
    # the request server waits out in-flight streams before closing
    await watchdog.stop()
    await canary.stop()
    health_sub.close()
    if status_server is not None:
        await status_server.stop()
    if not watchdog.fired:
        await served.stop(graceful_timeout_s=args.graceful_timeout)
    await clear_served.stop()
    if eplb_served is not None:
        await eplb_served.stop()
    for s in lora_served:
        await s.stop()
    engine.stop()
    await runtime.shutdown()
    if mh is not None:
        if any(not e.healthy for e in engines):
            # dead group (follower lost / engine crash): the distributed-
            # shutdown barrier would wait for a peer that isn't coming, and
            # jax's atexit hook would do the same — exit hard so the
            # supervisor restarts the whole group
            import os as _os

            _os._exit(2)
        mh.shutdown_jax()


if __name__ == "__main__":
    asyncio.run(main())
