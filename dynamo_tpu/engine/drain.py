"""Planned worker death: the drain coordinator (docs/operations.md §13).

A reclaim notice (spot/preemptible TPU reclaim, rolling upgrade) arrives with
a deadline — either on the ``/drain`` control endpoint (runtime/health.py) or
from a supervisor calling :meth:`DrainCoordinator.begin` directly. The
coordinator then runs the pipeline the fleet sim proves end to end
(sim/scenarios.py ``elastic-reclaim``):

1. flip this worker's discovery instance record to ``state=draining`` — the
   frontend and KvRouter stop routing new work here (llm/discovery.py folds
   draining instances into the exclusion set, same path as tripped breakers);
2. wait out short in-flight decodes inside the deadline budget (long ones are
   the frontend's job: its Migration layer replays them elsewhere, and the
   error-finish frames carry an evacuation annotation pointing the retry at
   this worker's sealed KV);
3. checkpoint warm state (sealed KV pages, radix order, queue manifest,
   weights by content-hash reference) to the G3 tier so the rescheduled
   replacement restores warm (engine/checkpoint.py).

The drain lease (:class:`DrainLedger`) brackets the whole operation; the
RESOURCE-LEAK drain-lease spec (tools/analysis/resources.py) proves every
path out of :meth:`begin` releases it — a leaked lease is a worker stuck
advertising ``draining`` with no drain running.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, Dict, List, Optional

from ..runtime import metrics as M
from ..runtime.config import (
    ENV_CKPT_DIR,
    ENV_DRAIN_DEADLINE_S,
    ENV_DRAIN_MARGIN_S,
    env_float,
    env_str,
)
from ..runtime.faults import FAULTS
from ..runtime.flight_recorder import get_flight_recorder
from ..runtime.logging import get_logger

log = get_logger("engine.drain")

# the drain pipeline leaves a timeline under this synthetic id (PR 17 gap):
# notice -> quiesce -> checkpoint on one /debug/requests?id=drain record,
# so "how did the last reclaim go" reads like any request post-mortem
DRAIN_FLIGHT_ID = "drain"


class DrainLedger:
    """At most one live drain lease per worker process."""

    def __init__(self):
        self._leases: Dict[int, float] = {}
        self._next = 1

    def acquire_drain(self, deadline_s: float) -> Optional[int]:
        """A lease token, or None when a drain is already in flight."""
        if self._leases:
            return None
        token = self._next
        self._next += 1
        self._leases[token] = deadline_s
        return token

    def release_drain(self, token: int) -> None:
        self._leases.pop(token, None)

    @property
    def draining(self) -> bool:
        return bool(self._leases)


class DrainCoordinator:
    """Owns one worker's planned-death pipeline. ``served`` is the
    registered endpoint handle (runtime/component.ServedEndpoint) whose
    metadata update flips the discovery record; ``engine`` is the TpuEngine
    (or dp facade) being drained."""

    def __init__(
        self,
        engine,
        served,
        *,
        ckpt_dir: Optional[str] = None,
        weights_ref: str = "",
        metrics_scope=None,
        on_drained: Optional[Callable[[], None]] = None,
    ):
        self.engine = engine
        self.served = served
        self.ckpt_dir = (
            ckpt_dir if ckpt_dir is not None else (env_str(ENV_CKPT_DIR, "") or None)
        )
        self.weights_ref = weights_ref
        self.ledger = DrainLedger()
        # fires after the drain completes (metadata flipped, KV checkpointed):
        # __main__ wires the process stop event here
        self.on_drained = on_drained
        self._evacuated = (
            metrics_scope.counter(
                M.DRAIN_EVACUATED_BLOCKS,
                "sealed KV blocks evacuated/checkpointed during drains",
            )
            if metrics_scope is not None else None
        )
        self._margin = (
            metrics_scope.gauge(
                M.DRAIN_DEADLINE_MARGIN,
                "seconds left on the reclaim deadline when the drain finished",
            )
            if metrics_scope is not None else None
        )

    def _queue_manifest(self) -> List[Dict[str, Any]]:
        """Request-queue manifest for the checkpoint: enough to audit what
        was in flight at the kill (the requests themselves are replayed by
        the frontend's Migration, not restored from here)."""
        out: List[Dict[str, Any]] = []
        for state_name, seqs in (
            ("running", getattr(self.engine, "_slots", None) or []),
            ("waiting", getattr(self.engine, "_waiting", None) or []),
        ):
            for st in seqs:
                req = getattr(st, "req", None)
                rid = getattr(req, "request_id", None)
                if rid is None:
                    continue
                out.append({
                    "request_id": rid,
                    "state": state_name,
                    "produced": int(getattr(st, "produced", 0) or 0),
                })
        return out

    async def _await_quiesce(self, budget_s: float, t0: float) -> bool:
        """Let short in-flight decodes run to completion inside the budget.
        True when the engine went idle; False when the budget ran out (the
        frontend migrates what is left when the process dies)."""
        while time.monotonic() - t0 < budget_s:
            snap = self.engine.snapshot()
            ranks = snap["ranks"] if "ranks" in snap else [snap]
            if sum(r["running"] + r["waiting"] for r in ranks) == 0:
                return True
            await asyncio.sleep(0.05)
        return False

    async def begin(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Run the drain. Idempotent: a second notice while one drain is in
        flight reports ``already=True`` and changes nothing."""
        await FAULTS.ainject("drain.notice")
        if deadline_s is None:
            deadline_s = env_float(ENV_DRAIN_DEADLINE_S, 30.0)
        margin_s = env_float(ENV_DRAIN_MARGIN_S, 2.0)
        token = self.ledger.acquire_drain(deadline_s)
        if token is None:
            return {"state": "draining", "already": True}
        flight = get_flight_recorder()
        flight.record(
            DRAIN_FLIGHT_ID, "drain_notice", deadline_s=deadline_s,
        )
        t0 = time.monotonic()
        try:
            await self.served.update_metadata({
                "state": "draining",
                "drain_deadline_s": deadline_s,
            })
            log.info("draining: deadline=%.1fs", deadline_s)
            quiesced = await self._await_quiesce(
                max(0.0, deadline_s - margin_s), t0
            )
            flight.record(
                DRAIN_FLIGHT_ID, "drain_quiesce", quiesced=quiesced,
                elapsed_s=round(time.monotonic() - t0, 3),
            )
            ckpt_blocks = 0
            if self.ckpt_dir:
                from .checkpoint import checkpoint_engine

                manifest = await checkpoint_engine(
                    self.engine, self.ckpt_dir,
                    queue=self._queue_manifest(),
                    weights_ref=self.weights_ref,
                )
                ckpt_blocks = len(manifest.get("blocks", ()))
                if self._evacuated is not None and ckpt_blocks:
                    self._evacuated.inc(ckpt_blocks)
            flight.record(
                DRAIN_FLIGHT_ID, "drain_checkpoint", blocks=ckpt_blocks,
            )
            margin = deadline_s - (time.monotonic() - t0)
            if self._margin is not None:
                self._margin.set(margin)
            log.info(
                "drain complete: quiesced=%s ckpt_blocks=%d margin=%.2fs",
                quiesced, ckpt_blocks, margin,
            )
            if self.on_drained is not None:
                self.on_drained()
            return {
                "state": "draining",
                "deadline_s": deadline_s,
                "quiesced": quiesced,
                "checkpoint_blocks": ckpt_blocks,
                "deadline_margin_s": margin,
            }
        finally:
            self.ledger.release_drain(token)
