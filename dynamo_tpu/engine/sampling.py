"""On-device batched sampling: greedy / temperature / top-k / top-p / min-p,
frequency / presence / repetition penalties, and top-N logprobs.

Logits never leave the device (vocab-sized transfers per step would saturate
the host link); only sampled token ids (+ small top-k logprob rows) come back.
All branches are tensor-masked — no data-dependent *shapes* — but the
expensive paths (full-vocab sort for top-k/top-p, [B,V] gumbel draw, [B,V]
penalty tables) are gated behind ``lax.cond`` on whether any request in the
batch actually enables them, so a greedy batch pays only an argmax. This
mirrors how the reference folds per-request sampling options in its
preprocessor (lib/llm/src/preprocessor.rs) and leaves the hot loop branchless.

Penalty semantics match vLLM/OpenAI:
- repetition_penalty: tokens seen in prompt OR output; logit>0 ? l/r : l*r
- frequency_penalty:  logits -= fp * count(token in output)
- presence_penalty:   logits -= pp * (token in output)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# top-N logprobs rows returned by the decode program when any request asks
# for them. OpenAI's schema allows top_logprobs up to 20, so the on-device
# top_k matches — requests are never silently clamped below what the API
# validated (lib/llm/src/protocols/openai/chat_completions/delta.rs analog).
TOP_LOGPROBS_K = 20


def apply_penalties(
    logits: jax.Array,             # [B, V] float32
    output_counts: jax.Array,      # [B, V] int32 generated-token counts
    prompt_mask: jax.Array,        # [B, V] int8/bool tokens present in prompt
    presence: jax.Array,           # [B]
    frequency: jax.Array,          # [B]
    repetition: jax.Array,         # [B]
) -> jax.Array:
    """Returns penalized logits. Free (one cond + passthrough) when the whole
    batch has penalties disabled."""

    def with_pen(l):
        counts_f = output_counts.astype(jnp.float32)
        out_seen = output_counts > 0
        seen = out_seen | (prompt_mask != 0)
        rep = jnp.where(l > 0, l / repetition[:, None], l * repetition[:, None])
        l = jnp.where(seen, rep, l)
        l = l - frequency[:, None] * counts_f
        l = l - presence[:, None] * out_seen.astype(jnp.float32)
        return l

    need = jnp.any(
        (presence != 0.0) | (frequency != 0.0) | (repetition != 1.0)
    )
    return jax.lax.cond(need, with_pen, lambda l: l, logits)


def _mask_topk_topp(
    logits: jax.Array,       # [B, V] (already penalized)
    temp_safe: jax.Array,    # [B, 1] clamped temperature
    top_k: jax.Array,        # [B] <=0 => disabled
    top_p: jax.Array,        # [B] >=1 => disabled
) -> jax.Array:
    """One descending sort serves both filters: the top-k cutoff is the kth
    sorted value; top-p is computed over the top-k-surviving prefix of the
    same sorted array (softmax in sorted order, cumulative mass)."""
    B, V = logits.shape
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]              # [B, V]

    k_eff = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))       # [B]
    k_idx = jnp.clip(k_eff - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]

    # top-p over the top-k set: positions >= k are excluded from the mass
    rank = jnp.arange(V)[None, :]
    in_topk = rank < k_eff[:, None]
    sorted_scaled = jnp.where(in_topk, sorted_desc / temp_safe, NEG_INF)
    probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    p = jnp.where(top_p >= 1.0, 1.0, top_p)[:, None]
    include = (cumprobs - probs_sorted < p) & in_topk
    count = jnp.maximum(include.sum(axis=-1), 1)                  # [B]
    cutoff_p = jnp.take_along_axis(sorted_desc, (count - 1)[:, None], axis=-1)

    cutoff = jnp.maximum(kth, cutoff_p)
    return jnp.where(logits >= cutoff, logits, NEG_INF)


def sample_tokens(
    logits: jax.Array,        # [B, V] float32
    seeds: jax.Array,         # [B] uint32 per-request seed
    steps: jax.Array,         # [B] int32 decode position (key = fold_in(seed, step))
    temperature: jax.Array,   # [B] 0 => greedy
    top_k: jax.Array,         # [B] int32, <=0 => disabled
    top_p: jax.Array,         # [B] float32, >=1 => disabled
    min_p: Optional[jax.Array] = None,  # [B] float32, <=0 => disabled
) -> jax.Array:
    """Returns sampled token ids [B] int32.

    Keys are derived statelessly from (seed, step): a seeded request
    reproduces its exact sample stream regardless of what else is in the
    batch or how long the engine has been running."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled_branch(l):
        temp_safe = jnp.maximum(temperature, 1e-6)[:, None]

        need_sort = jnp.any((top_k > 0) | (top_p < 1.0))
        l = jax.lax.cond(
            need_sort,
            lambda x: _mask_topk_topp(x, temp_safe, top_k, top_p),
            lambda x: x,
            l,
        )
        if min_p is not None:
            # p_i/p_max >= min_p  <=>  l_i >= l_max + temp*ln(min_p)
            max_l = jnp.max(l, axis=-1, keepdims=True)
            mp = jnp.clip(min_p, 1e-10, 1.0)[:, None]
            thresh = max_l + temp_safe * jnp.log(mp)
            l = jnp.where(
                (min_p > 0.0)[:, None] & (l < thresh), NEG_INF, l
            )

        def row_gumbel(seed, step):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            return jax.random.gumbel(key, (V,), dtype=jnp.float32)

        gumbel = jax.vmap(row_gumbel)(seeds, steps)
        return jnp.argmax(l / temp_safe + gumbel, axis=-1).astype(jnp.int32)

    any_sampled = jnp.any(temperature > 0.0)
    sampled = jax.lax.cond(
        any_sampled, sampled_branch, lambda l: greedy, logits
    )
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def logprobs_of(
    logits: jax.Array,        # [B, V] float32 (pre-penalty logits)
    token_ids: jax.Array,     # [B] the chosen tokens
) -> jax.Array:
    """Log-probability of each chosen token [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None].astype(jnp.int32), axis=-1)[:, 0]


def top_logprobs(
    logits: jax.Array,        # [B, V] float32
    need: jax.Array,          # scalar bool: any request wants top logprobs
    k: int = TOP_LOGPROBS_K,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k (ids, logprobs) rows, or zeros when nobody asked ([B,k] each).

    The cond keeps the top_k scan off the hot path for batches that don't
    request logprobs."""
    B, V = logits.shape

    def compute(l):
        vals, ids = jax.lax.top_k(l, k)
        lse = jax.nn.logsumexp(l, axis=-1, keepdims=True)
        return (vals - lse), ids.astype(jnp.int32)

    def zeros(l):
        return jnp.zeros((B, k), jnp.float32), jnp.zeros((B, k), jnp.int32)

    return jax.lax.cond(need, compute, zeros, logits)


def update_counts(
    output_counts: jax.Array,  # [B, V] int32
    tokens: jax.Array,         # [B] sampled this step
    active: jax.Array,         # [B] bool
    need: jax.Array,           # scalar bool: any penalties enabled
) -> jax.Array:
    """Scatter-add the sampled tokens into the per-slot output counts (only
    maintained while some request has penalties on)."""

    def upd(c):
        rows = jnp.arange(c.shape[0])
        return c.at[rows, tokens].add(active.astype(jnp.int32))

    return jax.lax.cond(need, upd, lambda c: c, output_counts)
