"""On-device batched sampling: greedy / temperature / top-k / top-p.

Logits never leave the device (vocab-sized transfers per step would saturate
PCIe/host); only the sampled token ids [B] come back. All branches are
tensor-masked (no data-dependent control flow) so one compiled program serves
every per-request sampling configuration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jax.Array,        # [B, V] float32
    seeds: jax.Array,         # [B] uint32 per-request seed
    steps: jax.Array,         # [B] int32 decode position (key = fold_in(seed, step))
    temperature: jax.Array,   # [B] 0 => greedy
    top_k: jax.Array,         # [B] int32, <=0 => disabled
    top_p: jax.Array,         # [B] float32, >=1 => disabled
) -> jax.Array:
    """Returns sampled token ids [B] int32.

    Keys are derived statelessly from (seed, step): a seeded request
    reproduces its exact sample stream regardless of what else is in the
    batch or how long the engine has been running."""
    B, V = logits.shape

    # top-k mask: keep the k highest logits per row
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]          # [B, V]
    k_idx = jnp.clip(jnp.where(top_k <= 0, V, top_k) - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    masked = jnp.where(logits >= kth, logits, NEG_INF)

    # top-p (nucleus) mask over the surviving set
    temp_safe = jnp.maximum(temperature, 1e-6)[:, None]
    probs_sorted = jax.nn.softmax(
        jnp.sort(masked / temp_safe, axis=-1)[:, ::-1], axis=-1
    )
    cumprobs = jnp.cumsum(probs_sorted, axis=-1)
    # number of tokens needed to reach top_p (at least 1)
    p = jnp.where(top_p >= 1.0, 1.0, top_p)[:, None]
    include = cumprobs - probs_sorted < p                      # [B, V] sorted order
    count = jnp.maximum(include.sum(axis=-1), 1)               # [B]
    sorted_masked = jnp.sort(masked, axis=-1)[:, ::-1]
    cutoff = jnp.take_along_axis(sorted_masked, (count - 1)[:, None], axis=-1)
    masked = jnp.where(masked >= cutoff, masked, NEG_INF)

    # gumbel-max sample at temperature; greedy where temperature == 0
    def row_gumbel(seed, step):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.gumbel(key, (V,), dtype=jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, steps)
    sampled = jnp.argmax(masked / temp_safe + gumbel, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def logprobs_of(
    logits: jax.Array,        # [B, V] float32
    token_ids: jax.Array,     # [B] the chosen tokens
) -> jax.Array:
    """Log-probability of each chosen token [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None].astype(jnp.int32), axis=-1)[:, 0]
