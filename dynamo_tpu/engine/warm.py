"""Warm-restore weight cache: fast worker restarts from a host-local cache.

TPU analog of the reference's warm-start machinery — chrek's CRIU container
checkpoint/restore of warmed workers (deploy/chrek, pairing with
vllm/main.py:79-120) and the gpu_memory_service's crash-surviving weight
ownership (lib/gpu_memory_service). CRIU and CUDA VMM have no TPU
equivalent, so the survey's prescribed design (SURVEY §2.4) applies: a
host-side memory-mappable weight cache + fast re-``device_put``.

First worker start parses the HF checkpoint (slow: safetensors decode,
dtype casts) and writes each tensor into one flat ``.npy`` directory keyed
by a config fingerprint; every restart after a crash or redeploy mmaps the
cache and ships bytes straight to the device. Combined with the XLA
compilation cache (persistent on disk), a restarted worker skips both the
parse and the compile — the "restore a warmed worker" outcome without CRIU.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

import numpy as np

from ..runtime.logging import get_logger

log = get_logger("engine.warm")

DEFAULT_CACHE_ROOT = os.environ.get(
    "DTPU_WARM_CACHE", os.path.expanduser("~/.cache/dynamo_tpu/warm")
)


# bump when the param-pytree layout changes (key names / shapes), so caches
# written by older code are invalidated instead of loaded under wrong specs
# (v2: MLA expert stacks renamed w_gate -> w_egate etc.)
PARAM_LAYOUT_VERSION = 2


def _fingerprint(source: str, cfg: Any) -> str:
    """Cache key: checkpoint path + mtime + model-config repr + layout ver."""
    try:
        mtime = str(os.path.getmtime(source))
    except OSError:
        mtime = "0"
    blob = json.dumps(
        [source, mtime, repr(cfg), PARAM_LAYOUT_VERSION], sort_keys=True
    ).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class WarmWeightCache:
    def __init__(self, root: Optional[str] = None):
        self.root = root or DEFAULT_CACHE_ROOT
        os.makedirs(self.root, exist_ok=True)

    def _dir(self, key: str) -> str:
        return os.path.join(self.root, key)

    def has(self, source: str, cfg: Any) -> bool:
        d = self._dir(_fingerprint(source, cfg))
        return os.path.exists(os.path.join(d, "MANIFEST.json"))

    # -- save -----------------------------------------------------------------
    def save(self, source: str, cfg: Any, params: Dict[str, Any]) -> str:
        """Flatten the param pytree to one .npy per tensor + a manifest.
        Atomic: the manifest lands last, so a crashed save never half-hits."""
        key = _fingerprint(source, cfg)
        d = self._dir(key)
        os.makedirs(d, exist_ok=True)
        flat = _flatten(params)
        manifest = []
        for name, arr in flat.items():
            a = np.asarray(arr)
            fname = name.replace("/", "__") + ".npy"
            tmp = os.path.join(d, fname + f".tmp{os.getpid()}")
            # bfloat16 has no numpy dtype: store the raw bytes as uint16
            # with the true dtype recorded in the manifest. Write through a
            # handle — np.save(path) would append another ".npy".
            with open(tmp, "wb") as f:
                if a.dtype.name == "bfloat16":
                    np.save(f, a.view(np.uint16), allow_pickle=False)
                    dtype = "bfloat16"
                else:
                    np.save(f, a, allow_pickle=False)
                    dtype = a.dtype.name
            os.replace(tmp, os.path.join(d, fname))
            manifest.append({"name": name, "file": fname, "dtype": dtype,
                             "shape": list(a.shape)})
        tmp = os.path.join(d, f"MANIFEST.json.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump({"key": key, "tensors": manifest}, f)
        os.replace(tmp, os.path.join(d, "MANIFEST.json"))
        log.info("warm cache saved: %s (%d tensors)", d, len(manifest))
        return d

    # -- load -----------------------------------------------------------------
    def load(self, source: str, cfg: Any) -> Optional[Dict[str, Any]]:
        """mmap every tensor and rebuild the pytree (host arrays; the engine
        device_puts them with its shardings). None on miss/corruption."""
        d = self._dir(_fingerprint(source, cfg))
        if not os.path.exists(os.path.join(d, "MANIFEST.json")):
            return None  # plain miss
        try:
            return load_manifest_dir(d)
        except Exception:
            # manifest present but tensors unreadable (partial cleanup,
            # tmpfs pressure): that's corruption, not a miss — say so
            log.exception("warm cache at %s unreadable; falling back to source", d)
            return None


def load_manifest_dir(d: str) -> Dict[str, Any]:
    """mmap every tensor of one manifest directory (the warm-cache / weight-
    service on-disk format) and rebuild the param pytree. Zero-copy: arrays
    are views over the mapped files, so a tmpfs-resident directory is a
    shared-memory import."""
    import jax.numpy as jnp

    with open(os.path.join(d, "MANIFEST.json")) as f:
        manifest = json.load(f)
    flat: Dict[str, Any] = {}
    for t in manifest["tensors"]:
        arr = np.load(os.path.join(d, t["file"]), mmap_mode="r",
                      allow_pickle=False)
        if t["dtype"] == "bfloat16":
            # view, not copy: reinterpret the mmap'd uint16 buffer
            arr = arr.view(jnp.bfloat16.dtype)
        flat[t["name"]] = arr
    return _unflatten(flat)


def _flatten(params: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            for i, lp in enumerate(v):
                out.update(_flatten(lp, f"{prefix}layers/{i}/"))
        elif isinstance(v, dict):
            out.update(_flatten(v, f"{prefix}{k}/"))
        else:
            out[f"{prefix}{k}"] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    layers: Dict[int, Dict[str, Any]] = {}
    for name, arr in flat.items():
        parts = name.split("/")
        if parts[0] == "layers":
            layers.setdefault(int(parts[1]), {})["/".join(parts[2:])] = arr
        else:
            node = params
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = arr
    if layers:
        params["layers"] = [layers[i] for i in sorted(layers)]
    return params


def load_params_warm(path: str, cfg: Any, cache: Optional[WarmWeightCache] = None):
    """Drop-in replacement for weights.load_params with warm-cache fast path."""
    from .weights import load_params

    cache = cache or WarmWeightCache()
    cached = cache.load(path, cfg)
    if cached is not None:
        log.info("warm restore: weights from cache (skipping checkpoint parse)")
        return cached
    params = load_params(path, cfg)
    try:
        cache.save(path, cfg, params)
    except Exception:
        log.exception("warm cache save failed (serving continues)")
    return params
