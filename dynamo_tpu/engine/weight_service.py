"""Out-of-process weight ownership: weights survive engine crashes.

TPU-native analog of the reference's gpu_memory_service
(lib/gpu_memory_service/README.md:1-50): there, a separate owner process
holds model weights in CUDA VMM so worker crashes don't lose them and
respawned workers *import* instead of reloading. CUDA VMM has no TPU
equivalent — TPU HBM is owned by the runtime, not mappable across
processes — so the survey-prescribed analog (SURVEY §2.4) applies at the
host layer:

- A **weight owner** process parses checkpoints ONCE and publishes each
  tensor as an mmap-able ``.npy`` file in a tmpfs directory (``/dev/shm``):
  host shared memory with filesystem naming.
- Workers **import** over a unix socket: the owner replies with the
  manifest directory; the worker maps the tensors zero-copy (no safetensors
  parse, no dtype casts, no disk I/O) and ``device_put``s straight from the
  shared pages.
- Imports are leased per connection: a worker killed with SIGKILL drops its
  socket and the owner reclaims its references, exactly like the
  reference's ownership handshake. Weight sets with live references refuse
  eviction.

The on-disk format is the warm-cache manifest (engine/warm.py) so the two
restore paths — same-process warm restart and cross-process import — share
one layout and one loader (``warm.load_manifest_dir``).

Wire protocol: JSON lines over a unix socket. Ops: import / release /
evict / stat / shutdown.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import shutil
import socket
import time
from typing import Any, Dict, Optional

from ..runtime.logging import get_logger
from .warm import WarmWeightCache, _fingerprint, load_manifest_dir

log = get_logger("engine.weight_service")

DEFAULT_ROOT = os.environ.get("DTPU_WEIGHT_SHM", "/dev/shm/dtpu_weights")


def _cfg_to_obj(cfg: Any) -> Optional[Dict[str, Any]]:
    if cfg is None:
        return None
    d = dataclasses.asdict(cfg)
    d["__kind__"] = type(cfg).__name__  # LlamaConfig / MoeConfig dispatch
    dt = d.get("dtype")
    if dt is not None and not isinstance(dt, str):
        import numpy as np

        d["dtype"] = np.dtype(dt).name if not hasattr(dt, "__name__") else dt.__name__
    return d


def _cfg_from_obj(obj: Optional[Dict[str, Any]]) -> Any:
    if obj is None:
        return None
    d = dict(obj)
    kind = d.pop("__kind__", "LlamaConfig")
    if kind == "MoeConfig":
        from ..models.moe import MoeConfig as cls
    elif kind == "MlaConfig":
        from ..models.mla import MlaConfig as cls
    elif kind == "GptOssConfig":
        from ..models.gptoss import GptOssConfig as cls
    else:
        from ..models.llama import LlamaConfig as cls
    dt = d.get("dtype")
    if isinstance(dt, str):
        import jax.numpy as jnp

        d["dtype"] = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                      "float16": jnp.float16}.get(dt, jnp.bfloat16)
    return cls(**d)


@dataclasses.dataclass
class _WeightSet:
    source: str
    dir: str
    refs: int = 0
    bytes: int = 0
    loaded_at: float = 0.0
    load_s: float = 0.0


class WeightOwner:
    """The owner process' server half."""

    def __init__(self, sock_path: str, root: Optional[str] = None):
        self.sock_path = sock_path
        self.root = root or DEFAULT_ROOT
        self.cache = WarmWeightCache(self.root)
        self._sets: Dict[str, _WeightSet] = {}
        self._loads: Dict[str, asyncio.Lock] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._stop = asyncio.Event()

    async def start(self) -> "WeightOwner":
        os.makedirs(self.root, exist_ok=True)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        self._server = await asyncio.start_unix_server(
            self._handle, path=self.sock_path
        )
        log.info("weight owner on %s (root %s)", self.sock_path, self.root)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if os.path.exists(self.sock_path):
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass

    async def wait_shutdown(self) -> None:
        await self._stop.wait()

    # -- load ---------------------------------------------------------------
    async def _ensure_loaded(self, source: str, cfg_obj) -> _WeightSet:
        ws = self._sets.get(source)
        if ws is not None:
            return ws
        lock = self._loads.setdefault(source, asyncio.Lock())
        async with lock:
            ws = self._sets.get(source)
            if ws is not None:
                return ws
            t0 = time.monotonic()
            cfg = _cfg_from_obj(cfg_obj)

            def _load():
                from .weights import config_from_hf, load_params

                c = cfg if cfg is not None else config_from_hf(source)
                d = self.cache._dir(_fingerprint(source, c))
                if not os.path.exists(os.path.join(d, "MANIFEST.json")):
                    params = load_params(source, c)
                    d = self.cache.save(source, c, params)
                return d

            d = await asyncio.get_running_loop().run_in_executor(None, _load)
            nbytes = sum(
                os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)
            )
            ws = _WeightSet(
                source=source, dir=d, bytes=nbytes,
                loaded_at=time.time(), load_s=time.monotonic() - t0,
            )
            self._sets[source] = ws
            log.info(
                "weights resident: %s -> %s (%.1f MB, %.2fs)",
                source, d, nbytes / 1e6, ws.load_s,
            )
            return ws

    # -- connection ---------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        # source -> [weight_set, count]: the set identity is pinned so a
        # force-evict + re-import between a worker's import and its
        # disconnect can't leak this connection's stale references onto the
        # NEW set (which would let a live lease be evicted)
        conn_refs: Dict[str, list] = {}
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    req = json.loads(line)
                    resp = await self._dispatch(req, conn_refs)
                except Exception as e:  # noqa: BLE001 — protocol error reply
                    resp = {"ok": False, "error": str(e)}
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            # lease reclaim: a SIGKILLed worker never sent release — its
            # socket EOF returns every reference it held (gms ownership
            # handshake semantics). Only the set the references were taken
            # on is decremented; an evicted-and-replaced set is left alone.
            for src, (ws, n) in conn_refs.items():
                if self._sets.get(src) is ws:
                    ws.refs = max(0, ws.refs - n)
            writer.close()

    async def _dispatch(self, req: dict, conn_refs: Dict[str, list]) -> dict:
        op = req.get("op")
        if op == "import":
            source = req["source"]
            ws = await self._ensure_loaded(source, req.get("cfg"))
            ws.refs += 1
            ent = conn_refs.get(source)
            if ent is not None and ent[0] is ws:
                ent[1] += 1
            else:
                # first import, or the previously-imported set was evicted
                # out from under this connection (its refs died with it)
                conn_refs[source] = [ws, 1]
            return {"ok": True, "dir": ws.dir, "bytes": ws.bytes,
                    "load_s": ws.load_s, "refs": ws.refs}
        if op == "release":
            source = req["source"]
            ws = self._sets.get(source)
            if ws is None:
                return {"ok": False, "error": "unknown weight set"}
            ent = conn_refs.get(source)
            if ent is None or ent[0] is not ws or ent[1] <= 0:
                return {"ok": False, "error": "no reference held"}
            ent[1] -= 1
            ws.refs = max(0, ws.refs - 1)
            return {"ok": True, "refs": ws.refs}
        if op == "evict":
            source = req["source"]
            ws = self._sets.get(source)
            if ws is None:
                return {"ok": False, "error": "unknown weight set"}
            if ws.refs > 0 and not req.get("force"):
                return {"ok": False, "error": f"{ws.refs} live references"}
            del self._sets[source]
            shutil.rmtree(ws.dir, ignore_errors=True)
            return {"ok": True}
        if op == "stat":
            return {"ok": True, "sets": [
                {"source": w.source, "dir": w.dir, "refs": w.refs,
                 "bytes": w.bytes, "load_s": w.load_s}
                for w in self._sets.values()
            ]}
        if op == "shutdown":
            self._stop.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}


class WeightServiceClient:
    """Worker half: sync (engine startup is synchronous). The connection is
    the lease — keep the client open for the worker's lifetime."""

    def __init__(self, sock_path: str, timeout: float = 600.0):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(sock_path)
        self._buf = b""

    def _call(self, req: dict) -> dict:
        self._sock.sendall(json.dumps(req).encode() + b"\n")
        while b"\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("weight owner closed the connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(f"weight service: {resp.get('error')}")
        return resp

    def import_params(self, source: str, cfg: Any = None):
        """Returns (params pytree of zero-copy mmap'd host arrays, info)."""
        resp = self._call({"op": "import", "source": source,
                           "cfg": _cfg_to_obj(cfg)})
        return load_manifest_dir(resp["dir"]), resp

    def release(self, source: str) -> None:
        self._call({"op": "release", "source": source})

    def stat(self) -> list:
        return self._call({"op": "stat"})["sets"]

    def evict(self, source: str, force: bool = False) -> None:
        self._call({"op": "evict", "source": source, "force": force})

    def shutdown_owner(self) -> None:
        self._call({"op": "shutdown"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def load_params_served(
    source: str, cfg: Any = None, sock_path: Optional[str] = None,
    warm_fallback: bool = True,
):
    """Engine-facing loader: import from the weight service when one is
    configured and reachable, else fall back to the local warm-cache path
    (or a plain checkpoint parse when ``warm_fallback`` is off — e.g. the
    engine ran with --no-warm-cache). Returns (params, client-or-None) —
    the caller must keep the client alive (it is the lease) and close it on
    clean shutdown."""
    sock_path = sock_path or os.environ.get("DTPU_WEIGHT_SERVICE")
    if sock_path:
        try:
            client = WeightServiceClient(sock_path)
            params, info = client.import_params(source, cfg)
            log.info(
                "weights imported from owner (%.1f MB shared, owner load %.2fs)",
                info["bytes"] / 1e6, info["load_s"],
            )
            return params, client
        except (OSError, ConnectionError, RuntimeError) as e:
            log.warning("weight service unavailable (%s); loading locally", e)
    if warm_fallback:
        from .warm import load_params_warm

        return load_params_warm(source, cfg), None
    from .weights import load_params

    return load_params(source, cfg), None


def main(argv=None) -> None:
    """``python -m dynamo_tpu.engine.weight_service`` — run a weight owner."""
    import argparse

    p = argparse.ArgumentParser(description="dynamo-tpu weight owner")
    p.add_argument("--sock", required=True, help="unix socket path")
    p.add_argument("--root", default=None, help=f"tmpfs dir (default {DEFAULT_ROOT})")
    p.add_argument("--preload", action="append", default=[],
                   help="checkpoint dir(s) to load at startup")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"],
                   help="force the JAX backend (the axon plugin pins itself "
                        "even under JAX_PLATFORMS=cpu — same flag as the "
                        "engine CLI)")
    args = p.parse_args(argv)
    # the owner never needs a TPU: checkpoint parse + host shm only. Apply
    # the platform override BEFORE any jax backend init so an owner on a TPU
    # host (or with a wedged device tunnel) stays pure-host.
    plat = args.platform or os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat.split(",")[0])

    async def run():
        owner = await WeightOwner(args.sock, args.root).start()
        for src in args.preload:
            await owner._ensure_loaded(src, None)
        try:
            await owner.wait_shutdown()
        finally:
            await owner.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
