"""DpEngineGroup: one worker process serving N data-parallel ranks.

Analog of the reference's dp_rank-aware workers: each dp_rank owns an
independent KV pool and decode batch, the router targets a specific
(worker_id, dp_rank), and non-selected ranks simply don't see the request
(reference: lib/llm/src/kv_router/scheduler.rs:543-560 iterating every
dp_rank per worker; components/src/dynamo/vllm/main.py:67 non-leader rank
processes idling behind one endpoint).

TPU-native shape: rank r runs its own TpuEngine over its own device slice
(``meshes[r]``) — on a multi-chip host the ranks are disjoint chip groups
doing replicated serving; in CI they share the virtual CPU mesh. Each rank
publishes KV events and load metrics stamped with its dp_rank, so the
router's radix tree and cost model see N independent pools behind one
instance id.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List

from ..runtime.engine import Context
from ..runtime.logging import get_logger
from .engine import TpuEngine

log = get_logger("engine.dp")


class DpEngineGroup:
    """Dispatches requests to the dp_rank the router selected."""

    def __init__(self, engines: List[TpuEngine]):
        if not engines:
            raise ValueError("DpEngineGroup needs at least one engine")
        self.engines = engines

    @property
    def dp_size(self) -> int:
        return len(self.engines)

    @property
    def healthy(self) -> bool:
        return all(e.healthy for e in self.engines)

    @property
    def on_crash(self):
        return self.engines[0].on_crash

    @on_crash.setter
    def on_crash(self, cb) -> None:
        # the watchdog's push hook fans out: any rank's crash trips it
        for e in self.engines:
            e.on_crash = cb

    def rank_of(self, request: Any) -> int:
        ann = request.get("annotations") if isinstance(request, dict) else (
            getattr(request, "annotations", None)
        )
        rank = int((ann or {}).get("dp_rank", 0))
        if not 0 <= rank < self.dp_size:
            log.warning("dp_rank %d out of range (dp=%d); using 0", rank, self.dp_size)
            rank = 0
        return rank

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        rank = self.rank_of(request)
        async for out in self.engines[rank].generate(request, context):
            yield out

    def snapshot(self) -> dict:
        return {
            "dp_size": self.dp_size,
            "ranks": [e.snapshot() for e in self.engines],
        }

    def stop(self) -> None:
        for e in self.engines:
            e.stop()
