"""Engine checkpoint/restore through the G3 disk tier.

The planned-death half of crash recovery (docs/operations.md §13): a worker
that received a reclaim notice serializes its *warm* state — sealed KV pages
in the exact dtype-headered block-file layout the G3 spill tier already uses
(kvbm/pool.py), the allocator's radix/LRU hash order, a request-queue
manifest, and the model weights by content-hash reference (engine/warm.py
fingerprint — never a weight copy) — so its replacement restores warm
instead of re-prefilling the fleet's working set from scratch. Analog of the
reference's CRIU-based chrek checkpointer, minus the process image: we
snapshot the state that is expensive to recompute, not the process.

Crash consistency: block files land first (each one atomically via
tmp+rename), the manifest rename is the single commit point. A death between
block writes and the manifest commit leaves no ``MANIFEST.json`` — restore
classifies that as a partial checkpoint and cold-boots instead of serving a
torn snapshot. Restore validates the manifest structure and every block
against the declared block format; any mismatch raises
:class:`CheckpointCorrupt` (manifest) or stops the import (block), never
imports garbage pages.

No wall-clock reads here: the sim drives these functions under its virtual
clock and pins same-seed byte identity, so manifests carry no timestamps.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..kvbm.pool import _read_block_file, _write_block_file
from ..runtime.config import ENV_CKPT_MAX_BLOCKS, env_int
from ..runtime.faults import FAULTS
from ..runtime.logging import get_logger

log = get_logger("engine.checkpoint")

MANIFEST_NAME = "MANIFEST.json"
FORMAT_VERSION = 1
_HASH_RE_WIDTH = 16  # hashes serialize as zero-padded 16-hex, like G3 files


class CheckpointCorrupt(RuntimeError):
    """Checkpoint failed validation — the caller must cold-boot, not serve it."""

    code = "checkpoint_corrupt"


def weights_ref_for(source: str, cfg: Any) -> str:
    """Content-hash reference for the weights (engine/warm.py fingerprint:
    checkpoint path + mtime + config + layout version). The checkpoint
    stores this REFERENCE; restore re-resolves weights through the warm
    cache / weight service rather than duplicating gigabytes per reclaim."""
    from .warm import _fingerprint

    return _fingerprint(source, cfg)


class CheckpointWriter:
    """Stages block files under ``<dir>/blocks/`` and commits the manifest
    atomically. ``begin_manifest`` hands out a tmp-file handle that MUST be
    discharged by ``commit_manifest`` or ``abort_manifest`` on every path —
    the checkpoint-manifest ResourceSpec (tools/analysis/resources.py) holds
    callers to that."""

    def __init__(self, ckpt_dir: str, max_blocks: Optional[int] = None):
        self.dir = ckpt_dir
        self.blocks_dir = os.path.join(ckpt_dir, "blocks")
        os.makedirs(self.blocks_dir, exist_ok=True)
        self.max_blocks = (
            env_int(ENV_CKPT_MAX_BLOCKS, 4096) if max_blocks is None else max_blocks
        )
        self.written: List[int] = []

    def _block_file(self, h: int) -> str:
        return os.path.join(self.blocks_dir, f"{h:016x}.kv")

    def write_block(self, h: int, block: np.ndarray) -> bool:
        """Durably write one sealed block; False once the cap is reached.
        Atomic per block: a crash mid-write leaves only a tmp file the
        manifest never references."""
        if len(self.written) >= self.max_blocks:
            return False
        FAULTS.inject("checkpoint.write")
        tmp = self._block_file(h) + f".tmp{os.getpid()}"
        _write_block_file(tmp, block)
        os.replace(tmp, self._block_file(h))
        self.written.append(h)
        return True

    def begin_manifest(self, manifest: Dict[str, Any]) -> str:
        tmp = os.path.join(self.dir, MANIFEST_NAME + f".tmp{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, sort_keys=True, indent=1)
            f.flush()
            os.fsync(f.fileno())
        return tmp

    def commit_manifest(self, tmp: str) -> None:
        # the injection sits BEFORE the rename: an armed checkpoint.manifest
        # fault models dying mid-commit — no manifest appears, and restore
        # must classify the directory as a partial checkpoint
        FAULTS.inject("checkpoint.manifest")
        os.replace(tmp, os.path.join(self.dir, MANIFEST_NAME))

    def abort_manifest(self, tmp: str) -> None:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass


def save_checkpoint(
    ckpt_dir: str,
    blocks: Iterable[Tuple[int, np.ndarray]],
    *,
    block_format: Dict[str, Any],
    radix_order: Optional[Sequence[int]] = None,
    queue: Sequence[Dict[str, Any]] = (),
    weights_ref: str = "",
    max_blocks: Optional[int] = None,
) -> Dict[str, Any]:
    """Write a complete checkpoint and commit its manifest.

    ``blocks`` yields ``(hash, array)`` in radix-LRU order (oldest first, the
    same order the allocator would evict) so a capped checkpoint keeps the
    hottest suffix droppable last on restore. ``block_format`` is either
    ``{"kind": "int8", "nbytes": N}`` (flat QuantizedBlockCodec buffers) or
    ``{"kind": "float", "dtype": name, "shape": [L, 2, bs, kvh, d]}``.
    Returns the committed manifest dict."""
    w = CheckpointWriter(ckpt_dir, max_blocks=max_blocks)
    stored: List[int] = []
    for h, arr in blocks:
        if not w.write_block(h, arr):
            break
        stored.append(h)
    manifest = {
        "version": FORMAT_VERSION,
        "blocks": [f"{h:0{_HASH_RE_WIDTH}x}" for h in stored],
        "block_format": dict(block_format),
        "radix": [
            f"{h:0{_HASH_RE_WIDTH}x}"
            for h in (stored if radix_order is None else radix_order)
        ],
        "queue": list(queue),
        "weights_ref": str(weights_ref),
    }
    handle = w.begin_manifest(manifest)
    try:
        w.commit_manifest(handle)
    except BaseException:
        w.abort_manifest(handle)
        raise
    return manifest


@dataclasses.dataclass
class CheckpointState:
    """A validated, committed checkpoint ready to restore from."""

    dir: str
    blocks: List[int]            # sealed-block hashes, radix-LRU order
    block_format: Dict[str, Any]
    radix: List[int]             # full radix/LRU snapshot (may exceed blocks)
    queue: List[Dict[str, Any]]  # request-queue manifest
    weights_ref: str

    def _block_file(self, h: int) -> str:
        return os.path.join(self.dir, "blocks", f"{h:016x}.kv")

    def load_block(self, h: int) -> np.ndarray:
        """One sealed block, validated against the manifest's block format."""
        FAULTS.inject("restore.read")
        try:
            arr = _read_block_file(self._block_file(h))
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorrupt(f"block {h:016x} unreadable: {e}") from e
        fmt = self.block_format
        if fmt.get("kind") == "int8":
            if arr.dtype != np.uint8 or arr.shape != (int(fmt["nbytes"]),):
                raise CheckpointCorrupt(
                    f"block {h:016x} is not the manifest's int8 codec buffer "
                    f"({arr.dtype} {arr.shape} vs nbytes={fmt['nbytes']})"
                )
        else:
            expect = tuple(fmt.get("shape", ()))
            if arr.shape != expect or arr.dtype.name != fmt.get("dtype"):
                raise CheckpointCorrupt(
                    f"block {h:016x} does not match the manifest block format "
                    f"({arr.dtype.name} {arr.shape} vs {fmt.get('dtype')} {expect})"
                )
        return arr


def _parse_hashes(raw: Any, what: str) -> List[int]:
    if not isinstance(raw, list):
        raise CheckpointCorrupt(f"manifest {what} is not a list")
    out = []
    for item in raw:
        try:
            out.append(int(item, 16))
        except (TypeError, ValueError):
            raise CheckpointCorrupt(f"manifest {what} entry {item!r} is not a hash")
    return out


def load_checkpoint(ckpt_dir: str) -> CheckpointState:
    """Validate and open a checkpoint. Raises :class:`CheckpointCorrupt` for
    anything short of a fully committed, structurally sound manifest — a
    missing manifest is the crash-consistent partial-checkpoint signature
    (blocks were staged but the commit rename never happened)."""
    FAULTS.inject("restore.read")
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        raise CheckpointCorrupt(
            "no committed manifest (absent or partial checkpoint)"
        )
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = f.read()
        doc = json.loads(manifest)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"manifest unreadable: {e}") from e
    if not isinstance(doc, dict) or doc.get("version") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            f"manifest version {doc.get('version') if isinstance(doc, dict) else doc!r} "
            f"!= {FORMAT_VERSION}"
        )
    fmt = doc.get("block_format")
    if not isinstance(fmt, dict) or fmt.get("kind") not in ("int8", "float"):
        raise CheckpointCorrupt(f"bad block_format {fmt!r}")
    blocks = _parse_hashes(doc.get("blocks"), "blocks")
    radix = _parse_hashes(doc.get("radix", doc.get("blocks")), "radix")
    queue = doc.get("queue", [])
    if not isinstance(queue, list):
        raise CheckpointCorrupt("manifest queue is not a list")
    state = CheckpointState(
        dir=ckpt_dir,
        blocks=blocks,
        block_format=fmt,
        radix=radix,
        queue=queue,
        weights_ref=str(doc.get("weights_ref", "")),
    )
    # every manifest-referenced block must exist: the manifest commits LAST,
    # so a missing file means someone truncated the directory after commit
    for h in blocks:
        if not os.path.isfile(state._block_file(h)):
            raise CheckpointCorrupt(f"manifest names missing block {h:016x}")
    return state


# ---------------------------------------------------------------------------
# TpuEngine capture/restore (the sim's mocker path drives the functions above
# directly; these two wrap them around a real engine's device state)
# ---------------------------------------------------------------------------

def _engine_block_format(engine) -> Dict[str, Any]:
    if engine.kv_quantized:
        return {"kind": "int8", "nbytes": int(engine._kv_codec().nbytes)}
    return {
        "kind": "float",
        "dtype": np.dtype(engine.mcfg.dtype).name,
        "shape": [
            engine.mcfg.num_layers, 2, engine.cfg.block_size,
            engine.mcfg.num_kv_heads, engine.mcfg.head_dim,
        ],
    }


def _encode_gathered(engine, pending, gathered) -> List[np.ndarray]:
    """Per-block host arrays from a device gather, in the engine's STORAGE
    format — the same encode the kvbm offload path performs
    (engine/engine.py _offload_fetch), so checkpoint files are bit-identical
    to G3 spill files."""
    n = len(pending)
    if engine.kv_quantized:
        codec = engine._kv_codec()
        pay = np.empty((n,) + codec.payload_shape, np.int8)
        scl = np.empty((n,) + codec.scales_shape, np.float32)
        for li, (kq, vq) in enumerate(gathered):
            pay[:, li, 0] = np.asarray(kq.data)
            pay[:, li, 1] = np.asarray(vq.data)
            scl[:, li, 0] = np.asarray(kq.scale)
            scl[:, li, 1] = np.asarray(vq.scale)
        return [codec.encode(pay[i], scl[i]) for i in range(n)]
    store_dtype = np.dtype(engine.mcfg.dtype)
    layers = []
    for k_dev, v_dev in gathered:
        k = np.asarray(k_dev, store_dtype)
        v = np.asarray(v_dev, store_dtype)
        layers.append(np.stack([k, v], axis=1))     # [n, 2, bs, kvh, d]
    arr = np.stack(layers, axis=1)                  # [n, L, 2, bs, kvh, d]
    return [arr[i].copy() for i in range(n)]


async def checkpoint_engine(
    engine,
    ckpt_dir: str,
    *,
    queue: Sequence[Dict[str, Any]] = (),
    weights_ref: str = "",
    max_blocks: Optional[int] = None,
) -> Dict[str, Any]:
    """Serialize a live engine's sealed prefix-cache pages (radix-LRU order,
    oldest first) + queue manifest to ``ckpt_dir``. Runs the device gather on
    the event loop (same ordering contract as the offload path) and the file
    writes in the default executor."""
    import asyncio

    alloc = engine.allocator
    pending = [
        (bid, alloc._hash_of[bid], 0)
        for bid in alloc._lru
        if bid in alloc._hash_of
    ]
    cap = env_int(ENV_CKPT_MAX_BLOCKS, 4096) if max_blocks is None else max_blocks
    if len(pending) > cap:
        pending = pending[-cap:]  # keep the hottest (most recent) suffix
    blocks: List[Tuple[int, np.ndarray]] = []
    if pending:
        gathered = engine._enqueue_offload_gather(pending)
        arrs = _encode_gathered(engine, pending, gathered)
        blocks = [(h, arr) for (_, h, _), arr in zip(pending, arrs)]
    loop = asyncio.get_event_loop()
    return await loop.run_in_executor(
        None,
        lambda: save_checkpoint(
            ckpt_dir, blocks,
            block_format=_engine_block_format(engine),
            radix_order=[h for _, h, _ in pending],
            queue=queue, weights_ref=weights_ref, max_blocks=cap,
        ),
    )


def _record_restore(result: Dict[str, Any], ckpt_dir: str) -> Dict[str, Any]:
    """Stamp the restore outcome on the worker's flight recorder under a
    synthetic id (like engine/drain.py's drain timeline): the restore
    classification is the first thing to read after an elastic respawn."""
    from ..runtime.flight_recorder import get_flight_recorder

    get_flight_recorder().record(
        "restore", "checkpoint_restore",
        mode=result["mode"], blocks=result["blocks"],
        queued=len(result.get("queue", ())), ckpt_dir=ckpt_dir,
        **({"reason": result["reason"]} if "reason" in result else {}),
    )
    return result


async def restore_engine(engine, ckpt_dir: str) -> Dict[str, Any]:
    """Restore sealed pages from a checkpoint into a fresh engine. Never
    raises on a bad checkpoint: corruption is DETECTED and reported as a
    cold boot (``{"mode": "cold", ...}``), the failure mode the chaos sim
    pins. Returns ``{"mode": "warm"|"partial"|"cold", "blocks": n,
    "queue": [...]}`` — ``partial`` means a torn block cut the import
    short but the content-addressed prefix before it is live (still a
    useful respawn, but the operator should know the tail is cold). The
    outcome also lands on the flight recorder as a ``checkpoint_restore``
    event under the synthetic ``restore`` timeline."""
    import asyncio

    loop = asyncio.get_event_loop()
    try:
        state = await loop.run_in_executor(None, load_checkpoint, ckpt_dir)
    except CheckpointCorrupt as e:
        log.warning("checkpoint at %s rejected (%s); cold boot", ckpt_dir, e)
        return _record_restore(
            {"mode": "cold", "blocks": 0, "queue": [], "reason": str(e)},
            ckpt_dir,
        )
    if state.block_format != _engine_block_format(engine):
        log.warning(
            "checkpoint block format %s does not match this engine (%s); "
            "cold boot", state.block_format, _engine_block_format(engine),
        )
        return _record_restore(
            {"mode": "cold", "blocks": 0, "queue": [], "reason": "format"},
            ckpt_dir,
        )
    imported = 0
    truncated = False
    window = 64
    for lo in range(0, len(state.blocks), window):
        batch = state.blocks[lo : lo + window]
        try:
            arrs = [
                await loop.run_in_executor(None, state.load_block, h)
                for h in batch
            ]
        except CheckpointCorrupt as e:
            # content-addressed pages already imported are valid — keep the
            # warm prefix, stop at the first torn block
            log.warning("restore stopped at bad block (%s)", e)
            truncated = True
            break
        if state.block_format["kind"] == "int8":
            arr = engine._kv_codec().decode_many(np.stack(arrs))
        else:
            arr = np.stack(arrs)
        imported += await engine.import_blocks(list(batch), arr)
    if not imported:
        mode = "cold"
    elif truncated or imported < len(state.blocks):
        mode = "partial"
    else:
        mode = "warm"
    return _record_restore(
        {"mode": mode, "blocks": imported, "queue": list(state.queue)},
        ckpt_dir,
    )
