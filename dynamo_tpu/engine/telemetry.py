"""Engine step telemetry: a cheap per-step stats hook + Prometheus projection.

The engine loop hands a ``StepStats`` to ``engine.stats_hook`` after every
prefill chunk, every consumed decode horizon, and every fused ``mixed``
continuous-batching step (one prefill chunk riding along with a decode step
through the unified ragged kernel — its batch_occupancy shows how full the
fused launch ran). The stats are host-side
scalars read off bookkeeping the loop already maintains — the hook NEVER
touches jit-traced code or forces a device sync (durations are host wall
time around executor calls; token counts come from ``_accept_tokens``'s own
``produced`` counters).

``EngineTelemetry`` is the standard consumer: it projects StepStats onto
the runtime metrics registry (histograms split by phase, occupancy/KV/queue
gauges, spec-decode acceptance) under the caller's hierarchy labels
(``dtpu_namespace``/``dtpu_component``), and logs any step slower than
``DTPU_SLOW_STEP_MS`` (default 1000 ms — tunneled-TPU horizons run hundreds
of ms; a multi-second step means the device stalled or the host fell
behind). ``bench.py`` attaches its own collector to the same hook to put
mean/p99 step time in the BENCH JSON.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, Optional

from ..runtime import metrics as M
from ..runtime.config import ENV_SLOW_STEP_MS, env_float
from ..runtime.logging import get_logger

log = get_logger("engine.telemetry")

# horizon consumption on tunneled devices sits around 0.1-1s; prefill chunks
# can reach seconds on first compile
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 15.0, 60.0)
_TOKEN_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)


@dataclasses.dataclass
class StepStats:
    """One engine-loop step, observed host-side."""

    phase: str                 # "prefill" | "decode" | "mixed"
    duration_s: float          # host wall time of the step's dispatch/consume
    batch_occupancy: int       # active (admitted, unfinished) slots
    batch_size: int            # configured max batch width
    tokens: int                # tokens processed: prefill chunk len / emitted
                               # ("mixed" fused steps count chunk + decode)
    queue_depth: int           # admission queue length (waiting requests)
    kv_active_blocks: int
    kv_free_blocks: int
    kv_total_blocks: int
    spec_acceptance: Optional[float] = None  # None unless spec decoding on
    # async host step-prep (engine/prep.py, DTPU_ASYNC_PREP): whether this
    # chunk-carrying step consumed a prebuilt pack, how long the prebuild
    # took (that time ran UNDER the previous step's device compute when
    # hit), and how long the dispatch still had to wait on it. None/0 on
    # decode-only steps and with async prep off.
    prep_hit: Optional[bool] = None
    prep_build_s: float = 0.0
    prep_wait_s: float = 0.0


class EngineTelemetry:
    """StepStats -> Prometheus + slow-step log. Construct one per engine
    with a scope already stamped with the component hierarchy (and a
    ``dp_rank`` label for dp groups); ranks share the underlying metric
    objects through the scope cache."""

    def __init__(self, scope: M.MetricsScope,
                 slow_step_s: Optional[float] = None):
        self.slow_step_s = (
            env_float(ENV_SLOW_STEP_MS, 1000.0) / 1e3
            if slow_step_s is None else slow_step_s
        )
        self.steps = 0
        self._dur = scope.histogram(
            M.STEP_DURATION_SECONDS,
            "engine step duration (host-observed), split by phase",
            extra_labels=("phase",), buckets=_STEP_BUCKETS,
        )
        self._tokens = scope.histogram(
            M.STEP_TOKENS, "tokens processed per engine step",
            extra_labels=("phase",), buckets=_TOKEN_BUCKETS,
        )
        self._occupancy = scope.gauge(
            M.BATCH_OCCUPANCY, "active sequences in the decode batch"
        )
        self._queue = scope.gauge(
            M.QUEUED_REQUESTS, "requests waiting in the engine admission queue"
        )
        self._kv_active = scope.gauge(
            M.KV_ACTIVE_BLOCKS, "KV blocks pinned by active sequences"
        )
        self._kv_free = scope.gauge(M.KV_FREE_BLOCKS, "free KV blocks")
        self._kv_total = scope.gauge(M.KV_TOTAL_BLOCKS, "configured KV blocks")
        self._decode_blocks = scope.gauge(
            M.WORKER_ACTIVE_DECODE_BLOCKS,
            "active decode blocks this worker reports to the router",
        )
        self._spec = scope.gauge(
            M.SPEC_ACCEPTANCE,
            "speculative decoding acceptance rate (emitted / drafted)",
        )
        self._slow = scope.counter(
            M.SLOW_STEPS_TOTAL, "steps slower than DTPU_SLOW_STEP_MS",
            extra_labels=("phase",),
        )
        self.slow_steps = 0
        # small rolling window + last-seen gauges for the /debug/worker
        # snapshot (runtime/health.py): step telemetry without a Prometheus
        # scrape-and-parse round trip
        self._recent: "collections.deque[StepStats]" = collections.deque(
            maxlen=128
        )
        self._last: Optional[StepStats] = None

    def snapshot(self) -> Dict[str, Any]:
        """The step-telemetry section of the worker's ``/debug/worker``
        document: rolling per-phase step-time means plus the last step's
        occupancy/queue/KV view."""
        recent = list(self._recent)
        by_phase: Dict[str, Dict[str, Any]] = {}
        for s in recent:
            agg = by_phase.setdefault(
                s.phase, {"steps": 0, "duration_sum_s": 0.0, "tokens": 0}
            )
            agg["steps"] += 1
            agg["duration_sum_s"] += s.duration_s
            agg["tokens"] += s.tokens
        phases = {
            phase: {
                "steps": agg["steps"],
                "mean_step_s": round(agg["duration_sum_s"] / agg["steps"], 6),
                "tokens": agg["tokens"],
            }
            for phase, agg in sorted(by_phase.items())
        }
        out: Dict[str, Any] = {
            "steps_total": self.steps,
            "slow_steps_total": self.slow_steps,
            "recent": phases,
        }
        last = self._last
        if last is not None:
            out["last"] = {
                "phase": last.phase,
                "batch_occupancy": last.batch_occupancy,
                "batch_size": last.batch_size,
                "queue_depth": last.queue_depth,
                "kv_active_blocks": last.kv_active_blocks,
                "kv_free_blocks": last.kv_free_blocks,
                "kv_total_blocks": last.kv_total_blocks,
            }
        return out

    def on_step(self, s: StepStats) -> None:
        try:
            self.steps += 1
            self._recent.append(s)
            self._last = s
            self._dur.observe(s.duration_s, phase=s.phase)
            if s.tokens > 0:
                self._tokens.observe(s.tokens, phase=s.phase)
            self._occupancy.set(s.batch_occupancy)
            self._queue.set(s.queue_depth)
            self._kv_active.set(s.kv_active_blocks)
            self._kv_free.set(s.kv_free_blocks)
            self._kv_total.set(s.kv_total_blocks)
            self._decode_blocks.set(s.kv_active_blocks)
            if s.spec_acceptance is not None:
                self._spec.set(s.spec_acceptance)
            if s.duration_s > self.slow_step_s:
                self.slow_steps += 1
                self._slow.inc(phase=s.phase)
                log.warning(
                    "slow %s step: %.0f ms (threshold %.0f ms; occupancy "
                    "%d/%d, queue %d, kv %d/%d blocks)",
                    s.phase, s.duration_s * 1e3, self.slow_step_s * 1e3,
                    s.batch_occupancy, s.batch_size, s.queue_depth,
                    s.kv_active_blocks, s.kv_total_blocks,
                )
        except Exception:
            # telemetry must never take the step loop down
            log.exception("step telemetry projection failed")
