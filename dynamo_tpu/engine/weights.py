"""HF checkpoint loading: safetensors -> llama param pytree.

Loads local HuggingFace-format checkpoints (config.json + *.safetensors) into
the functional param layout of models/llama.py. Works fully offline; when no
checkpoint is given the engine random-initializes (benchmark throughput does
not depend on trained weights).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig
from ..runtime.logging import get_logger

log = get_logger("engine.weights")


def config_from_hf(path: str) -> LlamaConfig:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_position=hf.get("max_position_embeddings", 8192),
        qkv_bias=hf.get("attention_bias", False)
        or hf.get("model_type", "") == "qwen2",
        qk_norm=hf.get("model_type", "") == "qwen3",
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def _open_safetensors(path: str):
    """Yields (name, np.ndarray) from all safetensors shards in ``path``."""
    from safetensors import safe_open  # available via transformers dep

    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_params(path: str, cfg: Optional[LlamaConfig] = None) -> Dict[str, Any]:
    """Map HF llama/qwen tensor names onto our pytree."""
    cfg = cfg or config_from_hf(path)
    layers: list = [dict() for _ in range(cfg.num_layers)]
    params: Dict[str, Any] = {"layers": layers}
    dt = cfg.dtype

    def put(arr: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(arr, dt)

    for name, w in _open_safetensors(path):
        if name == "model.embed_tokens.weight":
            params["embed"] = put(w)
        elif name == "model.norm.weight":
            params["final_norm"] = put(w)
        elif name == "lm_head.weight":
            params["lm_head"] = put(w.T)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            li = int(parts[2])
            rest = ".".join(parts[3:])
            lp = layers[li]
            # HF stores Linear as [out, in]; we use [in, out] -> transpose
            mapping = {
                "input_layernorm.weight": ("attn_norm", False),
                "post_attention_layernorm.weight": ("mlp_norm", False),
                "self_attn.q_proj.weight": ("wq", True),
                "self_attn.k_proj.weight": ("wk", True),
                "self_attn.v_proj.weight": ("wv", True),
                "self_attn.o_proj.weight": ("wo", True),
                "self_attn.q_proj.bias": ("bq", False),
                "self_attn.k_proj.bias": ("bk", False),
                "self_attn.v_proj.bias": ("bv", False),
                "self_attn.q_norm.weight": ("q_norm", False),
                "self_attn.k_norm.weight": ("k_norm", False),
                "mlp.gate_proj.weight": ("w_gate", True),
                "mlp.up_proj.weight": ("w_up", True),
                "mlp.down_proj.weight": ("w_down", True),
            }
            if rest in mapping:
                ours, transpose = mapping[rest]
                lp[ours] = put(w.T if transpose else w)
            else:
                log.debug("ignoring unmapped tensor %s", name)
    if cfg.tie_embeddings and "lm_head" not in params:
        pass  # lm_logits uses embed.T
    missing = [i for i, lp in enumerate(layers) if "wq" not in lp]
    if missing:
        raise ValueError(f"checkpoint at {path} missing layers {missing[:4]}...")
    log.info("loaded %d layers from %s", cfg.num_layers, path)
    return params
