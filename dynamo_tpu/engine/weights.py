"""HF checkpoint loading: safetensors -> llama param pytree.

Loads local HuggingFace-format checkpoints (config.json + *.safetensors) into
the functional param layout of models/llama.py. Works fully offline; when no
checkpoint is given the engine random-initializes (benchmark throughput does
not depend on trained weights).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig
from ..runtime.logging import get_logger

log = get_logger("engine.weights")


def config_from_hf(path: str) -> LlamaConfig:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    if hf.get("model_type", "") in ("deepseek_v2", "deepseek_v3"):
        return _mla_config_from_hf(hf)
    if hf.get("model_type", "") == "gpt_oss":
        return _gptoss_config_from_hf(hf)
    if hf.get("model_type", "") in ("gemma2", "gemma3", "gemma3_text"):
        return _gemma_config_from_hf(hf)
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_position=hf.get("max_position_embeddings", 8192),
        qkv_bias=hf.get("attention_bias", False)
        or hf.get("model_type", "") == "qwen2",
        qk_norm=hf.get("model_type", "") == "qwen3",
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def _mla_config_from_hf(hf: dict):
    """DeepSeek V2/V3 config.json -> MlaConfig (models/mla.py)."""
    from ..models.mla import MlaConfig

    return MlaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        q_lora_rank=hf.get("q_lora_rank") or 0,
        kv_lora_rank=hf["kv_lora_rank"],
        qk_nope_head_dim=hf["qk_nope_head_dim"],
        qk_rope_head_dim=hf["qk_rope_head_dim"],
        v_head_dim=hf["v_head_dim"],
        intermediate_size=hf["intermediate_size"],
        num_experts=hf.get("n_routed_experts") or 0,
        num_experts_per_tok=hf.get("num_experts_per_tok") or 2,
        moe_intermediate_size=hf.get("moe_intermediate_size") or 0,
        norm_topk_prob=hf.get("norm_topk_prob", True),
        moe_scoring=hf.get("scoring_func", "sigmoid"),
        routed_scaling_factor=hf.get("routed_scaling_factor", 1.0),
        num_shared_experts=hf.get("n_shared_experts") or 0,
        first_dense_layers=hf.get("first_k_dense_replace", 0),
        n_group=hf.get("n_group") or 1,
        topk_group=hf.get("topk_group") or 1,
        rope_interleave=hf.get("rope_interleave", True),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_position=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", False),
    )


def _gemma_config_from_hf(hf: dict):
    """Gemma 2 / Gemma 3 config.json -> GemmaConfig (models/gemma.py).
    Multimodal gemma3 nests the language model under text_config."""
    from ..models.gemma import GemmaConfig

    mt = hf.get("model_type", "")
    if mt == "gemma3" and "text_config" in hf:
        hf = hf["text_config"]
        mt = hf.get("model_type", "gemma3_text")
    is3 = mt in ("gemma3", "gemma3_text")
    lt = hf.get("layer_types") or ()
    layer_types = tuple(
        "sliding" if t == "sliding_attention" else "full" for t in lt
    )
    rope_scaling = hf.get("rope_scaling") or {}
    return GemmaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf.get("head_dim")
        or hf["hidden_size"] // hf["num_attention_heads"],
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_position=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", True),
        qk_norm=is3,
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar", 256)),
        sliding_window=hf.get("sliding_window") or 4096,
        layer_types=layer_types,
        sliding_pattern=hf.get("sliding_window_pattern", 6 if is3 else 2),
        attn_logit_softcap=hf.get("attn_logit_softcapping"),
        final_logit_softcap=hf.get("final_logit_softcapping"),
        rope_local_theta=hf.get("rope_local_base_freq") if is3 else None,
        rope_scaling_factor=float(rope_scaling.get("factor", 1.0)),
    )


def _gptoss_config_from_hf(hf: dict):
    """gpt-oss config.json -> GptOssConfig (models/gptoss.py)."""
    from ..models.gptoss import GptOssConfig

    rs = hf.get("rope_scaling") or {}
    yarn = rs.get("rope_type") == "yarn"
    return GptOssConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf["num_key_value_heads"],
        head_dim=hf.get("head_dim")
        or hf["hidden_size"] // hf["num_attention_heads"],
        intermediate_size=hf["intermediate_size"],
        num_experts=hf["num_local_experts"],
        num_experts_per_tok=hf["num_experts_per_tok"],
        sliding_window=hf.get("sliding_window") or 128,
        layer_types=tuple(hf.get("layer_types") or ()),
        rope_theta=hf.get("rope_theta", 150000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_position=hf.get("max_position_embeddings", 131072),
        qkv_bias=hf.get("attention_bias", True),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        rope_scaling_factor=rs.get("factor", 0.0) if yarn else 0.0,
        rope_beta_fast=rs.get("beta_fast", 32.0),
        rope_beta_slow=rs.get("beta_slow", 1.0),
        rope_truncate=rs.get("truncate", True),
        rope_original_max_position=rs.get(
            "original_max_position_embeddings",
            hf.get("max_position_embeddings", 4096),
        ),
    )


def _open_safetensors(path: str):
    """Yields (name, np.ndarray) from all safetensors shards in ``path``."""
    from safetensors import safe_open  # available via transformers dep

    files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_params(path: str, cfg: Optional[LlamaConfig] = None) -> Dict[str, Any]:
    """Map HF llama/qwen (or deepseek-MLA) tensor names onto our pytree."""
    from ..models.mla import MlaConfig

    from ..models.gptoss import GptOssConfig

    cfg = cfg or config_from_hf(path)
    if isinstance(cfg, MlaConfig):
        return _load_params_mla(path, cfg)
    if isinstance(cfg, GptOssConfig):
        return _load_params_gptoss(path, cfg)
    from ..models.gemma import GemmaConfig

    if isinstance(cfg, GemmaConfig):
        return _load_params_gemma(path, cfg)
    layers: list = [dict() for _ in range(cfg.num_layers)]
    params: Dict[str, Any] = {"layers": layers}
    dt = cfg.dtype

    def put(arr: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(arr, dt)

    for name, w in _open_safetensors(path):
        if name == "model.embed_tokens.weight":
            params["embed"] = put(w)
        elif name == "model.norm.weight":
            params["final_norm"] = put(w)
        elif name == "lm_head.weight":
            params["lm_head"] = put(w.T)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            li = int(parts[2])
            rest = ".".join(parts[3:])
            lp = layers[li]
            # HF stores Linear as [out, in]; we use [in, out] -> transpose
            mapping = {
                "input_layernorm.weight": ("attn_norm", False),
                "post_attention_layernorm.weight": ("mlp_norm", False),
                "self_attn.q_proj.weight": ("wq", True),
                "self_attn.k_proj.weight": ("wk", True),
                "self_attn.v_proj.weight": ("wv", True),
                "self_attn.o_proj.weight": ("wo", True),
                "self_attn.q_proj.bias": ("bq", False),
                "self_attn.k_proj.bias": ("bk", False),
                "self_attn.v_proj.bias": ("bv", False),
                "self_attn.q_norm.weight": ("q_norm", False),
                "self_attn.k_norm.weight": ("k_norm", False),
                "mlp.gate_proj.weight": ("w_gate", True),
                "mlp.up_proj.weight": ("w_up", True),
                "mlp.down_proj.weight": ("w_down", True),
            }
            if rest in mapping:
                ours, transpose = mapping[rest]
                lp[ours] = put(w.T if transpose else w)
            else:
                log.debug("ignoring unmapped tensor %s", name)
    if cfg.tie_embeddings and "lm_head" not in params:
        pass  # lm_logits uses embed.T
    missing = [i for i, lp in enumerate(layers) if "wq" not in lp]
    if missing:
        raise ValueError(f"checkpoint at {path} missing layers {missing[:4]}...")
    log.info("loaded %d layers from %s", cfg.num_layers, path)
    return params


def _deinterleave_rope_rows(w: np.ndarray, nope: int, rope: int, heads: int) -> np.ndarray:
    """DeepSeek checkpoints store rope projections in interleaved pair
    layout (HF applies apply_rotary_pos_emb_interleave when
    config.rope_interleave); our apply_rope is rotate-half. Permute each
    head's rope OUTPUT rows [0,1,2,...] -> [evens..., odds...] so the
    rotate-half pairing reproduces the interleaved semantics exactly.

    ``w`` is HF [out, in] with out = heads * (nope + rope)."""
    out, inner = w.shape
    w = w.reshape(heads, nope + rope, inner)
    rot = w[:, nope:, :]
    perm = np.concatenate([np.arange(0, rope, 2), np.arange(1, rope, 2)])
    w = np.concatenate([w[:, :nope, :], rot[:, perm, :]], axis=1)
    return w.reshape(out, inner)


def _load_params_gemma(path: str, cfg) -> Dict[str, Any]:
    """Map HF Gemma 2/3 tensors onto the models/gemma.py pytree (sandwich
    norms get their own names; multimodal gemma3 checkpoints prefix the
    text stack with language_model., stripped here — the vision tower is
    not loaded)."""
    layers: list = [dict() for _ in range(cfg.num_layers)]
    params: Dict[str, Any] = {"layers": layers}
    dt = cfg.dtype

    def put(arr: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(arr, dt)

    mapping = {
        "input_layernorm.weight": ("attn_norm", False),
        "post_attention_layernorm.weight": ("post_attn_norm", False),
        "pre_feedforward_layernorm.weight": ("pre_mlp_norm", False),
        "post_feedforward_layernorm.weight": ("post_mlp_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_norm.weight": ("q_norm", False),
        "self_attn.k_norm.weight": ("k_norm", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
    }
    for name, w in _open_safetensors(path):
        if name.startswith("language_model."):
            name = name[len("language_model."):]
        if name == "model.embed_tokens.weight":
            params["embed"] = put(w)
        elif name == "model.norm.weight":
            params["final_norm"] = put(w)
        elif name == "lm_head.weight":
            # untied finetunes: released gemma checkpoints tie, but a
            # finetune with tie_word_embeddings=false must not silently
            # fall back to embed.T (gemma.lm_logits prefers lm_head)
            params["lm_head"] = put(w.T)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            li = int(parts[2])
            rest = ".".join(parts[3:])
            if rest in mapping:
                ours, transpose = mapping[rest]
                layers[li][ours] = put(w.T if transpose else w)
            else:
                log.debug("ignoring unmapped tensor %s", name)
        else:
            log.debug("ignoring unmapped tensor %s", name)
    if not cfg.tie_embeddings and "lm_head" not in params:
        raise ValueError(
            f"checkpoint at {path} has tie_word_embeddings=false but no "
            "lm_head.weight"
        )
    missing = [i for i, lp in enumerate(layers) if "wq" not in lp]
    if missing:
        raise ValueError(f"checkpoint at {path} missing layers {missing[:4]}...")
    log.info("loaded %d gemma layers from %s", cfg.num_layers, path)
    return params


def _load_params_mla(path: str, cfg) -> Dict[str, Any]:
    """Map HF DeepSeek V2/V3 tensors onto the models/mla.py pytree.

    kv_b_proj [heads*(nope+v), rank] splits into the absorbed per-head
    up-projections: rows [:nope] -> w_uk [h, nope, rank] (index-identical),
    rows [nope:] -> w_uv [h, rank, v] (transposed). Rope output rows of
    q(_b)_proj and kv_a_proj_with_mqa are de-interleaved (see above)."""
    layers: list = [dict() for _ in range(cfg.num_layers)]
    params: Dict[str, Any] = {"layers": layers}
    experts: Dict[int, Dict[str, Dict[int, np.ndarray]]] = {}
    dt = cfg.dtype
    nope, rope, vd = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    nh, rank = cfg.num_heads, cfg.kv_lora_rank
    interleave = cfg.rope_interleave

    def deint(w: np.ndarray, pre: int, heads: int) -> np.ndarray:
        return _deinterleave_rope_rows(w, pre, rope, heads) if interleave else w

    def put(arr: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(arr, dt)

    for name, w in _open_safetensors(path):
        if name == "model.embed_tokens.weight":
            params["embed"] = put(w)
            continue
        if name == "model.norm.weight":
            params["final_norm"] = put(w)
            continue
        if name == "lm_head.weight":
            params["lm_head"] = put(w.T)
            continue
        if not name.startswith("model.layers."):
            log.debug("ignoring unmapped tensor %s", name)
            continue
        parts = name.split(".")
        li = int(parts[2])
        rest = ".".join(parts[3:])
        lp = layers[li]
        simple = {
            "input_layernorm.weight": ("attn_norm", False),
            "post_attention_layernorm.weight": ("mlp_norm", False),
            "self_attn.q_a_layernorm.weight": ("q_norm", False),
            "self_attn.kv_a_layernorm.weight": ("kv_norm", False),
            "self_attn.q_a_proj.weight": ("w_dq", True),
            "self_attn.o_proj.weight": ("wo", True),
            "mlp.gate_proj.weight": ("w_gate", True),
            "mlp.up_proj.weight": ("w_up", True),
            "mlp.down_proj.weight": ("w_down", True),
            "mlp.shared_experts.gate_proj.weight": ("w_shared_gate", True),
            "mlp.shared_experts.up_proj.weight": ("w_shared_up", True),
            "mlp.shared_experts.down_proj.weight": ("w_shared_down", True),
            "mlp.gate.weight": ("w_router", True),
        }
        if rest in simple:
            ours, transpose = simple[rest]
            lp[ours] = put(w.T if transpose else w)
        elif rest == "mlp.gate.e_score_correction_bias":
            lp["router_bias"] = jnp.asarray(w, jnp.float32)
        elif rest in ("self_attn.q_proj.weight", "self_attn.q_b_proj.weight"):
            ours = "wq" if rest == "self_attn.q_proj.weight" else "w_uq"
            lp[ours] = put(deint(w, nope, nh).T)
        elif rest == "self_attn.kv_a_proj_with_mqa.weight":
            # out rows = [latent (rank) | k_pe (rope)] — one "head" of rope
            lp["w_dkv"] = put(deint(w, rank, 1).T)
        elif rest == "self_attn.kv_b_proj.weight":
            kvb = w.reshape(nh, nope + vd, rank)
            lp["w_uk"] = put(kvb[:, :nope, :])
            lp["w_uv"] = put(np.swapaxes(kvb[:, nope:, :], 1, 2))
        elif parts[3] == "mlp" and parts[4] == "experts":
            ei, pname = int(parts[5]), parts[6]
            experts.setdefault(li, {}).setdefault(pname, {})[ei] = w
        else:
            log.debug("ignoring unmapped tensor %s", name)

    # stack per-expert FFN weights into [E, in, out]
    for li, groups in experts.items():
        for pname, ours in (
            ("gate_proj", "w_egate"), ("up_proj", "w_eup"),
            ("down_proj", "w_edown"),
        ):
            tensors = groups.get(pname, {})
            if len(tensors) != cfg.num_experts:
                raise ValueError(
                    f"layer {li}: {len(tensors)}/{cfg.num_experts} "
                    f"{pname} expert shards in checkpoint"
                )
            layers[li][ours] = put(
                np.stack([tensors[e].T for e in range(cfg.num_experts)])
            )
    missing = [
        i for i, lp in enumerate(layers)
        if ("wq" not in lp and "w_uq" not in lp) or "w_dkv" not in lp
    ]
    if missing:
        raise ValueError(f"checkpoint at {path} missing MLA layers {missing[:4]}...")
    log.info("loaded %d MLA layers from %s", cfg.num_layers, path)
    return params


# OCP MXFP4 e2m1 value table (public microscaling spec; also
# transformers.integrations.mxfp4.FP4_VALUES)
FP4_VALUES = (
    +0.0, +0.5, +1.0, +1.5, +2.0, +3.0, +4.0, +6.0,
    -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0,
)


def dequant_mxfp4(blocks: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Dequantize MXFP4 expert weights (the released gpt-oss checkpoints):
    ``blocks`` uint8 [E, out, G, B] packs two FP4 e2m1 nibbles per byte
    (low nibble first), ``scales`` uint8 [E, out, G] are e8m0 block
    exponents (bias 127). Returns float32 [E, in, out] — the input-major
    layout the bf16 checkpoints use."""
    lut = np.asarray(FP4_VALUES, np.float32)
    out = np.empty((*blocks.shape[:-1], blocks.shape[-1] * 2), np.float32)
    out[..., 0::2] = lut[blocks & 0x0F]
    out[..., 1::2] = lut[blocks >> 4]
    out *= np.exp2(scales.astype(np.int32) - 127)[..., None]
    out = out.reshape(*blocks.shape[:-2], -1)   # [E, out, in]
    return out.swapaxes(1, 2)                   # [E, in, out]


def _load_params_gptoss(path: str, cfg) -> Dict[str, Any]:
    """Map HF gpt-oss tensors onto the models/gptoss.py pytree. The fused
    per-expert projections (mlp.experts.gate_up_proj [E, H, 2I],
    down_proj [E, I, H]) are stored input-major in HF (used as x @ W), so
    they load without transposition; gate/up lanes stay interleaved (the
    expert kernel slices ::2 / 1::2 like the HF forward)."""
    layers: list = [dict() for _ in range(cfg.num_layers)]
    params: Dict[str, Any] = {"layers": layers}
    dt = cfg.dtype

    def put(arr: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(arr, dt)

    mapping = {
        "input_layernorm.weight": ("attn_norm", False),
        "post_attention_layernorm.weight": ("mlp_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        "self_attn.o_proj.bias": ("bo", False),
        "mlp.router.weight": ("w_router", True),
        "mlp.router.bias": ("b_router", False),
        "mlp.experts.gate_up_proj": ("w_gateup", False),
        "mlp.experts.gate_up_proj_bias": ("b_gateup", False),
        "mlp.experts.down_proj": ("w_edown", False),
        "mlp.experts.down_proj_bias": ("b_edown", False),
    }
    mx: Dict[int, Dict[str, np.ndarray]] = {}
    for name, w in _open_safetensors(path):
        if name == "model.embed_tokens.weight":
            params["embed"] = put(w)
        elif name == "model.norm.weight":
            params["final_norm"] = put(w)
        elif name == "lm_head.weight":
            params["lm_head"] = put(w.T)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            li = int(parts[2])
            rest = ".".join(parts[3:])
            if rest == "self_attn.sinks":
                layers[li]["sinks"] = jnp.asarray(w, jnp.float32)
            elif rest in mapping:
                ours, transpose = mapping[rest]
                layers[li][ours] = put(w.T if transpose else w)
            elif rest.startswith("mlp.experts.") and (
                rest.endswith("_blocks") or rest.endswith("_scales")
            ):
                # MXFP4-quantized release: dequantize the moment both halves
                # of a tensor arrive and DROP the raw halves — peak host
                # memory stays one tensor, not the whole quantized model
                part = rest.removeprefix("mlp.experts.")
                lay = mx.setdefault(li, {})
                lay[part] = w
                base = part.rsplit("_", 1)[0]
                b = lay.get(f"{base}_blocks")
                sc = lay.get(f"{base}_scales")
                if b is not None and sc is not None:
                    ours = {"gate_up_proj": "w_gateup", "down_proj": "w_edown"}[base]
                    layers[li][ours] = put(dequant_mxfp4(b, sc))
                    del lay[f"{base}_blocks"], lay[f"{base}_scales"]
            else:
                log.debug("ignoring unmapped tensor %s", name)
        else:
            log.debug("ignoring unmapped tensor %s", name)
    for li, parts_d in mx.items():
        if parts_d:  # an unpaired half means a truncated/corrupt checkpoint
            raise ValueError(
                f"layer {li}: MXFP4 tensors missing their other half: "
                f"{sorted(parts_d)}"
            )
    missing = [
        i for i, lp in enumerate(layers)
        if "wq" not in lp or "sinks" not in lp or "w_gateup" not in lp
    ]
    if missing:
        raise ValueError(
            f"checkpoint at {path} missing gpt-oss layers {missing[:4]}..."
        )
    log.info("loaded %d gpt-oss layers from %s", cfg.num_layers, path)
    return params
