"""Async host step-prep: build step N+1's packed chunk arrays under step N.

Host-side step preparation — bucket-padding the next prefill chunk's
token/position/block-id arrays and pushing them to the device — is pure
Python that used to run serially inside the dispatch executor, directly
bounding tok/s (the device bench is dead on this image, so host overhead
IS the measured number). ``ChunkPrep`` moves that work onto a dedicated
prep thread: the moment step N's device call is dispatched (device compute
is asynchronous from that point), the NEXT chunk's arrays are built — and
uploaded — while the device is still busy with step N.

Byte-identity with serial prep is structural, not best-effort:

- ``_build`` runs the engine's own ``_chunk_arrays`` (the one packing
  routine behind prefill, mixed and embed chunks) on an immutable
  snapshot — the prompt token ids and the chunk span's prompt-region block
  ids are both fixed at admission;
- ``take()`` hands a prebuilt result over ONLY when the serial path's key
  (request id, chunk start, chunk length, the exact block-id list) matches
  the snapshot the build used. Any divergence — cancellation, a
  migration/disagg resume moving ``prefill_pos``, block-table surgery —
  misses silently and the caller packs serially.

Block booking (``_book_decode_blocks``) deliberately stays on the event-
loop thread: the allocator is loop-owned (admission, commit and reap all
mutate it there), and the loop thread is already concurrent with in-flight
device compute — moving booking to another thread would buy races, not
overlap.

``DTPU_ASYNC_PREP`` (default on) gates the pipeline; ``StepStats`` carries
``prep_hit``/``prep_build_s``/``prep_wait_s`` so BENCH's
``detail.step_telemetry`` shows how much host prep actually overlapped.
Multihost engines keep serial prep (dispatch args there are part of the
leader's replay-ordered broadcast).
"""

from __future__ import annotations

import os
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

from ..runtime.config import ENV_ASYNC_PREP


def async_prep_enabled() -> bool:
    return os.environ.get(ENV_ASYNC_PREP, "1").lower() not in (
        "0", "", "false", "off"
    )


class ChunkPrep:
    """One per engine. ``schedule()`` is called from the dispatch executor
    right after a chunk's device call is in flight; ``take()`` is called by
    the next chunk's dispatch. Keys are exact-match, so a stale or wrong
    prebuild can never change what the device sees."""

    def __init__(
        self,
        chunk_arrays: Callable,          # engine._chunk_arrays (pure)
        upload: Optional[Callable] = None,  # jnp.asarray; None = host-only
    ):
        self._chunk_arrays = chunk_arrays
        self._upload = upload
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-prep"
        )
        # request_id -> (key, Future[(arrays, uploads, build_s)])
        self._pending: Dict[str, Tuple[tuple, Future]] = {}
        # stats of the most recent take(), consumed by engine._step_stats
        self.last: Optional[Dict[str, Any]] = None

    @staticmethod
    def _key(rid: str, token_ids, start: int, chunk_len: int,
             block_ids) -> tuple:
        # content-exact over precisely what _chunk_arrays reads: the
        # chunk's token SLICE (so a reused request id with an edited
        # prompt can never key-match a stale prebuild) plus the block-id
        # list. O(chunk) — same order as the packing it guards.
        return (
            rid, int(start), int(chunk_len),
            tuple(token_ids[start : start + chunk_len]),
            tuple(block_ids),
        )

    def _build(self, token_ids, start: int, chunk_len: int, block_ids):
        t0 = time.perf_counter()
        arrays = self._chunk_arrays(token_ids, start, chunk_len, block_ids)
        uploads = (
            tuple(self._upload(a) for a in arrays)
            if self._upload is not None else None
        )
        return arrays, uploads, time.perf_counter() - t0

    def schedule(self, rid: str, token_ids, start: int, chunk_len: int,
                 block_ids) -> None:
        """Prebuild (and pre-upload) one chunk. ``token_ids`` must be a
        list the caller will not mutate (the engine passes the fresh list
        ``Sequence.tokens()`` builds per call — no copy needed here, and a
        full-prompt copy per chunk would be O(prompt^2) per request);
        ``block_ids`` IS snapshotted (the engine mutates that list)."""
        if len(self._pending) > 64:
            # stale entries (cancelled/reaped requests) are bounded, not
            # tracked: correctness never depends on the cache's contents
            self._pending.clear()
        blocks = list(block_ids)
        key = self._key(rid, token_ids, start, chunk_len, blocks)
        self._pending[rid] = (
            key,
            self._ex.submit(self._build, token_ids, start, chunk_len, blocks),
        )

    def take(self, rid: str, token_ids, start: int, chunk_len: int,
             block_ids):
        """The prebuilt (arrays, uploads) for an exactly-matching chunk, or
        None (caller packs serially). Waits for an in-flight build — even a
        partial overlap beats rebuilding from scratch."""
        ent = self._pending.pop(rid, None)
        if ent is None:
            self.last = None
            return None
        key, fut = ent
        if key != self._key(rid, token_ids, start, chunk_len, block_ids):
            self.last = {"hit": False, "build_s": 0.0, "wait_s": 0.0}
            return None
        t0 = time.perf_counter()
        try:
            arrays, uploads, build_s = fut.result()
        except Exception:
            # a prep failure must never take the dispatch down; the serial
            # path recomputes (and surfaces any real packing error)
            self.last = {"hit": False, "build_s": 0.0, "wait_s": 0.0}
            return None
        self.last = {
            "hit": True,
            "build_s": build_s,
            "wait_s": time.perf_counter() - t0,
        }
        return arrays, uploads

    def pop_last(self) -> Optional[Dict[str, Any]]:
        last, self.last = self.last, None
        return last

    def stop(self) -> None:
        self._ex.shutdown(wait=False)
